"""Resource batch -> leaf tensors.

For every path in the compiled dictionary, enumerate the resource's slots
(the wildcard expansion of the path), recording per slot:

- ``mask``      prefix-presence bits (bit k = first k segments present on
                this chain). ``leaf present`` is bit len(segments).
- a *phantom slot* marks a broken chain (some map key absent): this is what
  distinguishes "missing key -> pattern FAIL" from "empty array -> vacuous
  PASS" (validate.go DefaultHandler vs validateArrayOfMaps over []).
- value features: type tag, interned string id (values stringify the Go way
  for wildcard comparison, pattern.go:309), i64 micro-units for anything
  quantity-parseable, plain-float/int flags and duration micro-seconds for
  the condition operators (variables/operator/*.go), bool value, and the
  top-level element index for gate alignment.

Paths rooted at ir.REQ_MARK resolve against the per-resource *request
envelope* (operation, namespace, userInfo — admission context) instead of
the resource body; ir.NSEFF_MARK resolves to the effective namespace
(resource name for Namespace kinds, utils.go checkNamespace).

Strings are interned into a per-batch dictionary; the NFA kernel matches
patterns against the *dictionary* once and verdicts gather by id — the
dedup that makes the string path cheap on device.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..utils.duration import DurationError, parse_duration
from ..utils.gofmt import value_to_string_for_equality
from ..utils.quantity import QuantityError, parse_quantity
from .compiler import STR_LEN, PolicyTensors
from .ir import NSEFF_MARK, NUM_MAX, NUM_SCALE, REQ_MARK, SEP

# type tags
T_ABSENT, T_NULL, T_BOOL, T_NUM, T_STR, T_OBJ, T_LIST = range(7)

# Canonical device-argument order. BATCH_ARRAYS are [B, ...] and shard over
# the mesh's data axis; DICT_ARRAYS are per-batch string-dictionary tables
# and replicate. pad_batch, the eval kernel signature, and the mesh
# shardings all derive from these two tuples — one source of truth.
BATCH_ARRAYS = (
    "mask", "slot_valid", "null_break", "type_tag", "str_id",
    "num_hi", "num_lo", "num_ok", "num_plain", "num_int",
    "dur_hi", "dur_lo", "dur_ok", "dur_any", "bool_val",
    "elem0", "kind_id", "host_flag", "live",
)
DICT_ARRAYS = ("str_bytes", "str_len", "str_has_glob")


@dataclass
class FlatBatch:
    n: int                    # batch size
    e: int                    # slots per path
    mask: np.ndarray          # [B, P, E] uint16 prefix bits
    slot_valid: np.ndarray    # [B, P, E] bool
    null_break: np.ndarray    # [B, P, E] bool — chain broke at a non-dict
                              # node (null/scalar/list parent): JMESPath
                              # field access yields null, NOT a missing-key
                              # error (engine/jmespath/interpreter._field)
    type_tag: np.ndarray      # [B, P, E] int8
    str_id: np.ndarray        # [B, P, E] int32 (-1 none)
    num_val: np.ndarray       # [B, P, E] int64 (host-side reference)
    num_hi: np.ndarray        # [B, P, E] int32 high limb (value >> 31)
    num_lo: np.ndarray        # [B, P, E] int32 low limb (value & 0x7FFFFFFF)
    num_ok: np.ndarray        # [B, P, E] bool (k8s-quantity-parseable)
    num_plain: np.ndarray     # [B, P, E] bool (plain strconv float)
    num_int: np.ndarray       # [B, P, E] bool (python/Go int value)
    dur_hi: np.ndarray        # [B, P, E] int32 duration micro-seconds limbs
    dur_lo: np.ndarray        # [B, P, E] int32
    dur_ok: np.ndarray        # [B, P, E] bool (duration-parseable, not "0")
    dur_any: np.ndarray       # [B, P, E] bool (duration-parseable incl "0")
    bool_val: np.ndarray      # [B, P, E] bool
    elem0: np.ndarray         # [B, P, E] int32 top-level element index (-1)
    kind_id: np.ndarray       # [B] int32 (-1 unknown kind)
    host_flag: np.ndarray     # [B] bool — needs the CPU oracle
    live: np.ndarray          # [B] bool — real resource (False = mesh pad;
                              # a real resource may legitimately have zero
                              # valid slots when every path crosses an
                              # empty array, so liveness is explicit)
    # string dictionary
    str_bytes: np.ndarray     # [V, STR_LEN] uint8
    str_len: np.ndarray       # [V] int32
    str_has_glob: np.ndarray  # [V] bool ('*' or '?' byte present)
    strings: list[str]

    def device_args(self) -> tuple:
        """Canonical argument order for ops.eval.build_eval_fn output."""
        return tuple(getattr(self, k) for k in BATCH_ARRAYS + DICT_ARRAYS)


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def pad_to_buckets(batch: FlatBatch) -> tuple["FlatBatch", int]:
    """Pad the data-dependent axes (batch B, slots-per-path E, dictionary V)
    up to powers of two so XLA compiles one kernel per shape *bucket*
    instead of one per distinct admission batch. Padded batch rows carry
    ``live=False``; padded slots carry ``slot_valid=False`` (the natural
    encoding for unused slots); padded dictionary rows are never gathered
    because no slot references their ids. Returns (padded, original_n)."""
    from dataclasses import replace

    b, e = batch.n, batch.e
    v = int(batch.str_len.shape[0])
    b2, e2, v2 = _next_pow2(b), _next_pow2(e), _next_pow2(v)
    if (b2, e2, v2) == (b, e, v):
        return batch, b

    updates: dict = {"n": b2, "e": e2}
    for name in BATCH_ARRAYS + ("num_val",):
        x = getattr(batch, name)
        width = [(0, b2 - b)] + [(0, 0)] * (x.ndim - 1)
        if x.ndim == 3:
            width[2] = (0, e2 - e)
        fill = -1 if name in ("kind_id", "str_id", "elem0") else 0
        updates[name] = np.pad(x, width, constant_values=fill)
    for name in DICT_ARRAYS:
        x = getattr(batch, name)
        width = [(0, v2 - v)] + [(0, 0)] * (x.ndim - 1)
        updates[name] = np.pad(x, width, constant_values=0)
    return replace(batch, **updates), b


class _Interner:
    def __init__(self):
        self.index: dict[str, int] = {}
        self.strings: list[str] = []

    def intern(self, s: str) -> int:
        i = self.index.get(s)
        if i is None:
            i = len(self.strings)
            self.index[s] = i
            self.strings.append(s)
        return i


def _value_to_micro(value) -> int | None:
    try:
        if isinstance(value, bool):
            return None
        if isinstance(value, float):
            # decode the shortest decimal repr (the JSON token) rather than
            # the exact binary double: "0.1" means 100000 micro, and repr
            # artifacts like 0.30000000000000004 take the host lane — the
            # same decision the native flattener makes from the token text
            micro = parse_quantity(repr(value)) * NUM_SCALE
        elif isinstance(value, int):
            from fractions import Fraction

            micro = Fraction(value) * NUM_SCALE
        elif isinstance(value, str):
            micro = parse_quantity(value) * NUM_SCALE
        else:
            return None
    except (QuantityError, ValueError, OverflowError):
        return None
    if micro.denominator != 1 or abs(micro.numerator) > NUM_MAX:
        return None
    return int(micro)


def _digit_capped(s: str) -> bool:
    """True when the leading number part has more than 36 digits — beyond
    the native flattener's exact __int128 range. Mirrors the counting loop
    in ktpu_flatten.cpp quantity_to_micro: ASCII-trim, optional sign, then
    digits with a single embedded dot."""
    s = s.strip(" \t\n\r\f\v")
    i = 0
    if i < len(s) and s[i] in "+-":
        i += 1
    n = 0
    seen_dot = False
    for ch in s[i:]:
        if "0" <= ch <= "9":
            n += 1
            if n > 36:
                return True
        elif ch == "." and not seen_dot:
            seen_dot = True
        else:
            break
    return False


def _needs_host_parse(s: str) -> bool:
    """True when the string could parse differently under unicode-aware
    rules (str.strip(), regex \\d, float()) than under the ASCII grammar
    the device lanes and the native flattener implement: any unicode
    whitespace/decimal digit, or the \\x1c-\\x1f controls str.isspace()
    accepts. Such leaves route the resource to the CPU oracle."""
    import unicodedata

    for ch in s:
        o = ord(ch)
        if 0x1C <= o <= 0x1F:
            return True
        if o > 0x7F and (ch.isspace() or unicodedata.category(ch) == "Nd"):
            return True
    return False


def _duration_micro(value: str) -> int | None:
    """Go-duration parse -> micro-seconds. ``dur_ok`` (strict) additionally
    excludes the literal "0" (operator.go:82 parseDuration); ``dur_any``
    keeps it (duration.go's deprecated Duration* handlers accept it)."""
    try:
        secs = parse_duration(value)
    except DurationError:
        return None
    micro = round(secs * 1_000_000)
    if abs(micro) > NUM_MAX:
        return None
    return micro


def _effective_namespace(resource: dict) -> str:
    meta = resource.get("metadata") or {}
    if resource.get("kind") == "Namespace":
        return meta.get("name") or ""
    return meta.get("namespace") or ""


def _enumerate_slots(resource, segments: list[str], request: dict,
                     ns_eff: str):
    """Yield (mask, elem0, leaf_value_or_None, leaf_present, null_break)
    for every chain of ``segments`` through the resource (or the request
    envelope / the effective-namespace synthetic). A phantom slot (leaf None
    + short mask) marks a broken chain; ``null_break`` records that the
    break happened at a node that exists but is not a map — the JMESPath
    fork resolves such a path to null instead of raising NotFound
    (interpreter._field), which conditions treat as a null key, not an
    unresolved variable. Empty arrays yield nothing."""
    if segments and segments[0] == NSEFF_MARK:
        return [(0b11, -1, ns_eff, True, False)]
    if segments and segments[0] == REQ_MARK:
        root = request
        segments = segments[1:]
        base_mask = 0b11 if request else 0b1
        if not segments:
            return [(base_mask, -1, None, False, False)]
        offset = 1
    else:
        root = resource
        base_mask = 0b1
        offset = 0

    out = []

    def walk(node, i: int, mask: int, elem0: int):
        if i == len(segments):
            out.append((mask, elem0, node, True, False))
            return
        seg = segments[i]
        bit = 1 << (i + 1 + offset)
        if seg == "*":
            if not isinstance(node, list):
                # a list pattern over an existing non-list node is a
                # structural mismatch (validateResourceElement array case)
                out.append((mask, elem0, None, False, True))
                return
            for idx, el in enumerate(node):
                walk(el, i + 1, mask | bit, idx if elem0 < 0 else elem0)
        else:
            if not isinstance(node, dict):
                out.append((mask, elem0, None, False, True))
                return
            if seg not in node:
                out.append((mask, elem0, None, False, False))
                return
            walk(node[seg], i + 1, mask | bit, elem0)

    if root is None or (offset == 1 and not request):
        return [(base_mask, -1, None, False, False)]
    walk(root, 0, base_mask, -1)  # bit 0: the root itself
    return out


def flatten_batch(resources: list[dict], tensors: PolicyTensors,
                  max_slots: int = 16,
                  requests: list[dict] | None = None) -> FlatBatch:
    """``requests`` optionally supplies per-resource admission envelopes
    (operation/namespace/userInfo) backing REQ_MARK paths; a background
    scan passes none and request.* condition keys resolve as absent, the
    same way the oracle's scan context leaves them unresolved."""
    B, P = len(resources), tensors.n_paths
    path_segments = [p.split(SEP) for p in tensors.paths]
    envelopes = requests if requests is not None else [{}] * B

    # first pass: find E
    all_slots: list[list] = []
    e_needed = 1
    host_flag = np.zeros(B, dtype=bool)
    for b, resource in enumerate(resources):
        row = []
        ns_eff = _effective_namespace(resource) if isinstance(resource, dict) else ""
        env = envelopes[b] or {}
        for segs in path_segments:
            slots = _enumerate_slots(resource, segs, env, ns_eff)
            if len(slots) > max_slots:
                host_flag[b] = True
                slots = slots[:max_slots]
            e_needed = max(e_needed, len(slots))
            row.append(slots)
        all_slots.append(row)
    E = e_needed

    interner = _Interner()
    mask = np.zeros((B, P, E), dtype=np.uint16)
    slot_valid = np.zeros((B, P, E), dtype=bool)
    null_break = np.zeros((B, P, E), dtype=bool)
    type_tag = np.full((B, P, E), T_ABSENT, dtype=np.int8)
    str_id = np.full((B, P, E), -1, dtype=np.int32)
    num_val = np.zeros((B, P, E), dtype=np.int64)
    num_ok = np.zeros((B, P, E), dtype=bool)
    num_plain = np.zeros((B, P, E), dtype=bool)
    num_int = np.zeros((B, P, E), dtype=bool)
    dur_val = np.zeros((B, P, E), dtype=np.int64)
    dur_ok = np.zeros((B, P, E), dtype=bool)
    dur_any = np.zeros((B, P, E), dtype=bool)
    bool_val = np.zeros((B, P, E), dtype=bool)
    elem0 = np.full((B, P, E), -1, dtype=np.int32)
    kind_id = np.full(B, -1, dtype=np.int32)

    for b, resource in enumerate(resources):
        kind = (resource.get("kind") or "") if isinstance(resource, dict) else ""
        kind_id[b] = tensors.kind_index.get(kind, -1)
        for p in range(P):
            for e, (m, e0, value, leaf, nbrk) in enumerate(all_slots[b][p]):
                mask[b, p, e] = m
                slot_valid[b, p, e] = True
                null_break[b, p, e] = nbrk
                elem0[b, p, e] = e0
                if not leaf:
                    continue
                if value is None:
                    type_tag[b, p, e] = T_NULL
                elif isinstance(value, bool):
                    type_tag[b, p, e] = T_BOOL
                    bool_val[b, p, e] = value
                    str_id[b, p, e] = interner.intern("true" if value else "false")
                elif isinstance(value, (int, float)):
                    type_tag[b, p, e] = T_NUM
                    num_int[b, p, e] = isinstance(value, int)
                    s = value_to_string_for_equality(value)
                    if len(s) <= STR_LEN:
                        str_id[b, p, e] = interner.intern(s)
                    n = _value_to_micro(value)
                    if n is not None:
                        num_val[b, p, e] = n
                        num_ok[b, p, e] = True
                        num_plain[b, p, e] = True
                    else:
                        host_flag[b] = True
                elif isinstance(value, str):
                    type_tag[b, p, e] = T_STR
                    if len(value.encode("utf-8")) <= STR_LEN:
                        str_id[b, p, e] = interner.intern(value)
                    else:
                        host_flag[b] = True
                    if _needs_host_parse(value):
                        # unicode-sensitive parse: leave the numeric lanes
                        # empty and let the oracle evaluate this resource
                        host_flag[b] = True
                        continue
                    if _digit_capped(value):
                        # >36-digit number part: exact range exceeded
                        host_flag[b] = True
                        continue
                    try:
                        int(value, 10)
                        num_int[b, p, e] = True  # strconv.ParseInt-able
                    except ValueError:
                        pass
                    n = _value_to_micro(value)
                    if n is not None:
                        num_val[b, p, e] = n
                        num_ok[b, p, e] = True
                        try:
                            float(value)
                            num_plain[b, p, e] = True
                        except ValueError:
                            pass
                    d = _duration_micro(value)
                    if d is not None:
                        dur_val[b, p, e] = d
                        dur_any[b, p, e] = True
                        dur_ok[b, p, e] = value != "0"
                elif isinstance(value, dict):
                    type_tag[b, p, e] = T_OBJ
                else:
                    type_tag[b, p, e] = T_LIST

    num_hi = (num_val >> 31).astype(np.int32)
    num_lo = (num_val & 0x7FFFFFFF).astype(np.int32)
    dur_hi = (dur_val >> 31).astype(np.int32)
    dur_lo = (dur_val & 0x7FFFFFFF).astype(np.int32)

    V = max(1, len(interner.strings))
    str_bytes = np.zeros((V, STR_LEN), dtype=np.uint8)
    str_len = np.zeros(V, dtype=np.int32)
    str_has_glob = np.zeros(V, dtype=bool)
    for i, s in enumerate(interner.strings):
        bs = s.encode("utf-8")[:STR_LEN]
        str_bytes[i, : len(bs)] = np.frombuffer(bs, dtype=np.uint8)
        str_len[i] = len(bs)
        str_has_glob[i] = "*" in s or "?" in s

    return FlatBatch(
        n=B, e=E, mask=mask, slot_valid=slot_valid, null_break=null_break,
        type_tag=type_tag,
        str_id=str_id, num_val=num_val, num_hi=num_hi, num_lo=num_lo,
        num_ok=num_ok, num_plain=num_plain, num_int=num_int,
        dur_hi=dur_hi, dur_lo=dur_lo, dur_ok=dur_ok, dur_any=dur_any,
        bool_val=bool_val,
        elem0=elem0, kind_id=kind_id, host_flag=host_flag,
        live=np.ones(B, dtype=bool),
        str_bytes=str_bytes, str_len=str_len, str_has_glob=str_has_glob,
        strings=interner.strings,
    )
