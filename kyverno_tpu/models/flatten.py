"""Resource batch -> leaf tensors.

For every path in the compiled dictionary, enumerate the resource's slots
(the wildcard expansion of the path), recording per slot:

- ``mask``      prefix-presence bits (bit k = first k segments present on
                this chain). ``leaf present`` is bit len(segments).
- a *phantom slot* marks a broken chain (some map key absent): this is what
  distinguishes "missing key -> pattern FAIL" from "empty array -> vacuous
  PASS" (validate.go DefaultHandler vs validateArrayOfMaps over []).
- value features: type tag, interned string id (values stringify the Go way
  for wildcard comparison, pattern.go:309), i64 micro-units for anything
  quantity-parseable, plain-float/int flags and duration micro-seconds for
  the condition operators (variables/operator/*.go), bool value, and the
  top-level element index for gate alignment.

Paths rooted at ir.REQ_MARK resolve against the per-resource *request
envelope* (operation, namespace, userInfo — admission context) instead of
the resource body; ir.NSEFF_MARK resolves to the effective namespace
(resource name for Namespace kinds, utils.go checkNamespace).

Strings are interned into a per-batch dictionary; the NFA kernel matches
patterns against the *dictionary* once and verdicts gather by id — the
dedup that makes the string path cheap on device.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass

import numpy as np

from ..runtime import featureplane
from ..utils.duration import DurationError, parse_duration
from ..utils.gofmt import value_to_string_for_equality
from ..utils.quantity import QuantityError, parse_quantity
from .compiler import STR_LEN, PolicyTensors
from .ir import NSEFF_MARK, NUM_MAX, NUM_SCALE, REQ_MARK, SEP

# type tags
T_ABSENT, T_NULL, T_BOOL, T_NUM, T_STR, T_OBJ, T_LIST = range(7)

# Canonical device-argument order. BATCH_ARRAYS are [B, ...] and shard over
# the mesh's data axis; DICT_ARRAYS are per-batch string-dictionary tables
# and replicate. pad_batch, the eval kernel signature, and the mesh
# shardings all derive from these two tuples — one source of truth.
BATCH_ARRAYS = (
    "mask", "slot_valid", "null_break", "type_tag", "str_id",
    "num_hi", "num_lo", "num_ok", "num_plain", "num_int",
    "dur_hi", "dur_lo", "dur_ok", "dur_any", "bool_val",
    "elem0", "kind_id", "host_flag", "live",
)
DICT_ARRAYS = ("str_bytes", "str_len", "str_has_glob")

# Packed transfer format. The 16 per-cell lanes compress into two uint32
# words per cell, because every *value* lane (num/dur/bool) is a pure
# function of the interned string: those move to a [V, 5] dictionary table
# gathered back by str_id on device. The per-cell words:
#   word0: str_id + 1                     (0 = no interned string)
#   word1: mask(16) | type_tag(3)<<16 | slot_valid<<19 | null_break<<20
#          | num_int<<21 | (elem0 + 1)<<22   (8 bits; > ELEM0_CAP -> host)
# and one uint32 per resource:
#   bmeta: (kind_id + 1)(16) | host_flag<<16 | live<<17
# The dictionary value table [V, 5] uint32:
#   d0: num_lo(31) | num_ok<<31        d1: num_hi (two's complement)
#   d2: dur_lo(31) | dur_ok<<31        d3: dur_hi (two's complement)
#   d4: str_len(7) | has_glob<<7 | bool_val<<8 | dur_any<<9 | num_plain<<10
# Cutting the admission/scan H2D from ~35 bytes/cell over 19 arrays to
# ~8 bytes/cell over 4 arrays is what makes the tunnel-attached TPU viable
# for the 1M-resource background scan (BASELINE config 5).
PACKED_BATCH_ARRAYS = ("cells", "bmeta")
PACKED_DICT_ARRAYS = ("str_bytes", "dictv")
ELEM0_CAP = 254  # largest representable first-element index

# Shared pad fill-value table for EVERY batch-padding site (bucket padding
# here, mesh-multiple padding in parallel/mesh.py). Lanes that encode ids
# as row indices pad with -1 ("no entry"); everything else pads with the
# natural zero (dead slot / not live). Deriving both paths from one table
# is what keeps a FlatBatch schema change from desynchronizing the mesh
# pad from the bucket pad again.
PAD_FILL = {"kind_id": -1, "str_id": -1, "elem0": -1}


def pad_fill(name: str) -> int:
    """Fill value for padding lane ``name`` (BATCH_ARRAYS / DICT_ARRAYS /
    num_val); unlisted lanes zero-fill."""
    return PAD_FILL.get(name, 0)


def _assemble_blob(cells, bmeta, str_bytes, dictv):
    """Concatenate the packed arrays into one uint32 transfer buffer.
    ops.eval._split_blob is the device-side inverse."""
    B, P, E = cells.shape[:3]
    V = int(dictv.shape[0])
    sw = np.ascontiguousarray(str_bytes).view(np.uint32)
    blob = np.concatenate([
        cells.reshape(-1), bmeta.reshape(-1),
        dictv.reshape(-1), sw.reshape(-1),
    ])
    return blob, (B, P, E, V)


@dataclass
class FlatBatch:
    n: int                    # batch size
    e: int                    # slots per path
    mask: np.ndarray          # [B, P, E] uint16 prefix bits
    slot_valid: np.ndarray    # [B, P, E] bool
    null_break: np.ndarray    # [B, P, E] bool — chain broke at a non-dict
                              # node (null/scalar/list parent): JMESPath
                              # field access yields null, NOT a missing-key
                              # error (engine/jmespath/interpreter._field)
    type_tag: np.ndarray      # [B, P, E] int8
    str_id: np.ndarray        # [B, P, E] int32 (-1 none)
    num_val: np.ndarray       # [B, P, E] int64 (host-side reference)
    num_hi: np.ndarray        # [B, P, E] int32 high limb (value >> 31)
    num_lo: np.ndarray        # [B, P, E] int32 low limb (value & 0x7FFFFFFF)
    num_ok: np.ndarray        # [B, P, E] bool (k8s-quantity-parseable)
    num_plain: np.ndarray     # [B, P, E] bool (plain strconv float)
    num_int: np.ndarray       # [B, P, E] bool (python/Go int value)
    dur_hi: np.ndarray        # [B, P, E] int32 duration micro-seconds limbs
    dur_lo: np.ndarray        # [B, P, E] int32
    dur_ok: np.ndarray        # [B, P, E] bool (duration-parseable, not "0")
    dur_any: np.ndarray       # [B, P, E] bool (duration-parseable incl "0")
    bool_val: np.ndarray      # [B, P, E] bool
    elem0: np.ndarray         # [B, P, E] int32 top-level element index (-1)
    kind_id: np.ndarray       # [B] int32 (-1 unknown kind)
    host_flag: np.ndarray     # [B] bool — needs the CPU oracle
    live: np.ndarray          # [B] bool — real resource (False = mesh pad;
                              # a real resource may legitimately have zero
                              # valid slots when every path crosses an
                              # empty array, so liveness is explicit)
    # string dictionary
    str_bytes: np.ndarray     # [V, STR_LEN] uint8
    str_len: np.ndarray       # [V] int32
    str_has_glob: np.ndarray  # [V] bool ('*' or '?' byte present)
    strings: list[str]

    def device_args(self) -> tuple:
        """Canonical argument order for ops.eval.build_eval_fn output."""
        return tuple(getattr(self, k) for k in BATCH_ARRAYS + DICT_ARRAYS)

    def packed_args(self) -> tuple:
        """(cells, bmeta, str_bytes, dictv) for build_eval_fn_packed —
        the transfer-thin form (see PACKED_BATCH_ARRAYS). Cached: admission
        retries and the scan pipeline reuse the same FlatBatch."""
        packed = getattr(self, "_packed", None)
        if packed is None:
            packed = pack_batch(self)
            object.__setattr__(self, "_packed", packed)
        return packed

    def packed_blob(self) -> tuple[np.ndarray, tuple[int, int, int, int]]:
        """One contiguous uint32 buffer + (B, P, E, V) static shape for
        build_eval_fn_blob. A single host->device transfer: the tunnel
        that fronts remote TPU chips charges a fixed round-trip per array,
        so 4 packed arrays cost ~4x the latency of their total bytes."""
        blob = getattr(self, "_blob", None)
        if blob is None:
            blob = _assemble_blob(*self.packed_args())
            object.__setattr__(self, "_blob", blob)
        return blob

    def to_flat(self) -> "FlatBatch":
        return self


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def pad_to_buckets(batch: FlatBatch) -> tuple["FlatBatch", int]:
    """Pad the data-dependent axes (batch B, slots-per-path E, dictionary V)
    up to powers of two so XLA compiles one kernel per shape *bucket*
    instead of one per distinct admission batch. Padded batch rows carry
    ``live=False``; padded slots carry ``slot_valid=False`` (the natural
    encoding for unused slots); padded dictionary rows are never gathered
    because no slot references their ids. Returns (padded, original_n)."""
    from dataclasses import replace

    b, e = batch.n, batch.e
    v = int(batch.str_len.shape[0])
    b2, e2, v2 = _next_pow2(b), _next_pow2(e), _next_pow2(v)
    if (b2, e2, v2) == (b, e, v):
        return batch, b

    updates: dict = {"n": b2, "e": e2}
    for name in BATCH_ARRAYS + ("num_val",):
        x = getattr(batch, name)
        width = [(0, b2 - b)] + [(0, 0)] * (x.ndim - 1)
        if x.ndim == 3:
            width[2] = (0, e2 - e)
        updates[name] = np.pad(x, width, constant_values=pad_fill(name))
    for name in DICT_ARRAYS:
        x = getattr(batch, name)
        width = [(0, v2 - v)] + [(0, 0)] * (x.ndim - 1)
        updates[name] = np.pad(x, width, constant_values=0)
    return replace(batch, **updates), b


def pack_batch(batch: FlatBatch) -> tuple:
    """Compress a FlatBatch into the packed transfer form
    (cells uint32 [B,P,E,2], bmeta uint32 [B], str_bytes, dictv uint32 [V,5]).

    The dictionary value rows are scattered from the cell lanes rather than
    re-analyzed from the strings: within one batch every cell referencing a
    dictionary row agrees on that row's value lanes for its type class
    (num lanes are shared by T_NUM/T_STR referents — a JSON number and the
    equal string intern the same text and micro value; dur lanes are set
    only by T_STR cells; bool only by T_BOOL), so last-write-wins is exact.
    Rows referenced by no cell of a class leave that class's bits zero, and
    the device unpack gates each class by type_tag, so the bits are never
    read. Resources whose elem0 exceeds ELEM0_CAP take the host lane (the
    oracle re-walks the original document, so capping is correct)."""
    u32 = np.uint32
    sid_w = (batch.str_id.astype(np.int64) + 1).astype(u32)
    e0 = batch.elem0.astype(np.int64)
    e0_over = e0 > ELEM0_CAP - 1
    e0_w = np.minimum(e0 + 1, 255).astype(u32)
    meta = (
        batch.mask.astype(u32)
        | (batch.type_tag.astype(u32) << 16)
        | (batch.slot_valid.astype(u32) << 19)
        | (batch.null_break.astype(u32) << 20)
        | (batch.num_int.astype(u32) << 21)
        | (e0_w << 22)
    )
    cells = np.stack([sid_w, meta], axis=-1)

    # a numeric/duration value on a string too long to intern has no
    # dictionary row to carry it — route the resource to the CPU oracle
    # (mirrors ktpu_flatten_packed's long-text handling)
    lost = ((batch.num_ok | batch.dur_any) & (batch.str_id < 0)).any(axis=(1, 2))
    host = batch.host_flag | e0_over.any(axis=(1, 2)) | lost
    bmeta = (
        (batch.kind_id.astype(np.int64) + 1).astype(u32)
        | (host.astype(u32) << 16)
        | (batch.live.astype(u32) << 17)
    )

    V = int(batch.str_len.shape[0])
    d = np.zeros((V, 5), dtype=u32)
    sid = batch.str_id.ravel()
    tag = batch.type_tag.ravel()
    ref = sid >= 0

    numsel = ref & ((tag == T_NUM) | (tag == T_STR))
    i = sid[numsel]
    d[i, 0] = (batch.num_lo.ravel()[numsel].astype(np.int64) & 0x7FFFFFFF).astype(u32) \
        | (batch.num_ok.ravel()[numsel].astype(u32) << 31)
    d[i, 1] = batch.num_hi.ravel()[numsel].astype(u32)
    plain = np.zeros(V, dtype=u32)
    plain[i] = batch.num_plain.ravel()[numsel].astype(u32)

    dursel = ref & (tag == T_STR)
    i = sid[dursel]
    d[i, 2] = (batch.dur_lo.ravel()[dursel].astype(np.int64) & 0x7FFFFFFF).astype(u32) \
        | (batch.dur_ok.ravel()[dursel].astype(u32) << 31)
    d[i, 3] = batch.dur_hi.ravel()[dursel].astype(u32)
    durany = np.zeros(V, dtype=u32)
    durany[i] = batch.dur_any.ravel()[dursel].astype(u32)

    boolv = np.zeros(V, dtype=u32)
    boolsel = ref & (tag == T_BOOL)
    i = sid[boolsel]
    boolv[i] = batch.bool_val.ravel()[boolsel].astype(u32)

    d[:, 4] = (
        batch.str_len.astype(u32)
        | (batch.str_has_glob.astype(u32) << 7)
        | (boolv << 8)
        | (durany << 9)
        | (plain << 10)
    )
    return cells, bmeta, batch.str_bytes, d


def unpack_batch(cells, bmeta, str_bytes, dictv, xp=np):
    """Inverse of pack_batch: reconstruct the 22 build_eval_fn arguments.

    Works on numpy arrays (tests, host fallback) or traced jax arrays
    (inside build_eval_fn_packed's jit, where XLA fuses the bit ops and
    dictionary gathers into the evaluation kernel)."""
    w0 = cells[..., 0]
    meta = cells[..., 1]
    str_id = w0.astype(xp.int32) - 1
    mask = (meta & 0xFFFF).astype(xp.uint16)
    type_tag = ((meta >> 16) & 7).astype(xp.int8)
    slot_valid = ((meta >> 19) & 1).astype(bool)
    null_break = ((meta >> 20) & 1).astype(bool)
    num_int = ((meta >> 21) & 1).astype(bool)
    elem0 = ((meta >> 22) & 0xFF).astype(xp.int32) - 1

    sid_safe = xp.maximum(str_id, 0)
    present = str_id >= 0
    tag_i = type_tag.astype(xp.int32)
    is_numlike = (tag_i == T_NUM) | (tag_i == T_STR)
    is_str = tag_i == T_STR
    is_bool = tag_i == T_BOOL

    def gather(col):
        return xp.take(dictv[:, col], sid_safe)

    d0, d1, d2, d3, d4 = (gather(c) for c in range(5))
    num_ok = ((d0 >> 31) & 1).astype(bool) & present & is_numlike
    num_lo = xp.where(num_ok, (d0 & 0x7FFFFFFF).astype(xp.int32), 0)
    num_hi = xp.where(num_ok, d1.astype(xp.int32), 0)
    num_plain = ((d4 >> 10) & 1).astype(bool) & present & is_numlike
    dur_any = ((d4 >> 9) & 1).astype(bool) & present & is_str
    dur_ok = ((d2 >> 31) & 1).astype(bool) & present & is_str
    dur_lo = xp.where(dur_any, (d2 & 0x7FFFFFFF).astype(xp.int32), 0)
    dur_hi = xp.where(dur_any, d3.astype(xp.int32), 0)
    bool_val = ((d4 >> 8) & 1).astype(bool) & present & is_bool
    num_int = num_int & is_numlike

    kind_id = (bmeta & 0xFFFF).astype(xp.int32) - 1
    host_flag = ((bmeta >> 16) & 1).astype(bool)
    live = ((bmeta >> 17) & 1).astype(bool)
    str_len = (dictv[:, 4] & 0x7F).astype(xp.int32)
    str_has_glob = ((dictv[:, 4] >> 7) & 1).astype(bool)
    return (mask, slot_valid, null_break, type_tag, str_id, num_hi, num_lo,
            num_ok, num_plain, num_int, dur_hi, dur_lo, dur_ok, dur_any,
            bool_val, elem0, kind_id, host_flag, live,
            str_bytes, str_len, str_has_glob)


@dataclass
class PackedBatch:
    """Flattened batch in the packed transfer form — the native
    flattener's direct output (ktpu_flatten_packed). Carries exactly what
    the device kernels consume; the 22 unpacked lanes and the decoded
    string list materialize lazily for oracle/debug consumers."""

    n: int
    e: int
    cells: np.ndarray         # [B, P, E, 2] uint32
    bmeta: np.ndarray         # [B] uint32
    str_bytes: np.ndarray     # [V, STR_LEN] uint8
    dictv: np.ndarray         # [V, 5] uint32

    def packed_args(self) -> tuple:
        return (self.cells, self.bmeta, self.str_bytes, self.dictv)

    def packed_blob(self) -> tuple[np.ndarray, tuple[int, int, int, int]]:
        blob = getattr(self, "_blob", None)
        if blob is None:
            blob = _assemble_blob(*self.packed_args())
            object.__setattr__(self, "_blob", blob)
        return blob

    @property
    def strings(self) -> list[str]:
        out = getattr(self, "_strings", None)
        if out is None:
            lens = self.dictv[:, 4] & 0x7F
            out = [
                bytes(self.str_bytes[i, : lens[i]]).decode(
                    "utf-8", "surrogateescape")
                for i in range(int(self.dictv.shape[0]))
            ]
            object.__setattr__(self, "_strings", out)
        return out

    def to_flat(self) -> "FlatBatch":
        """Unpack into the eager lane form (tests, host-side consumers)."""
        flat = getattr(self, "_flat", None)
        if flat is None:
            lanes = unpack_batch(self.cells, self.bmeta, self.str_bytes,
                                 self.dictv, xp=np)
            kw = dict(zip(BATCH_ARRAYS + DICT_ARRAYS, lanes))
            num_val = (kw["num_hi"].astype(np.int64) << 31) | kw["num_lo"]
            flat = FlatBatch(n=self.n, e=self.e, num_val=num_val,
                             strings=self.strings, **kw)
            object.__setattr__(self, "_flat", flat)
        return flat


def pad_packed(cells: np.ndarray, bmeta: np.ndarray,
               multiple: int) -> tuple[np.ndarray, np.ndarray, int]:
    """Pad the packed batch axis to a multiple of the mesh size. Zero fill
    is the natural dead encoding: sid word 0 = no string, meta 0 = invalid
    slot, bmeta 0 = unknown kind + not live."""
    b = cells.shape[0]
    padded = (b + multiple - 1) // multiple * multiple
    if padded == b:
        return cells, bmeta, b
    pad = padded - b
    cells = np.pad(cells, [(0, pad)] + [(0, 0)] * (cells.ndim - 1))
    bmeta = np.pad(bmeta, (0, pad))
    return cells, bmeta, b


def pad_to_buckets_packed(batch: PackedBatch) -> tuple[PackedBatch, int]:
    """Power-of-two bucket padding for the packed form (admission batching:
    one XLA compile per shape bucket, zero fill = dead rows/slots/strings).
    Returns (padded, original_n)."""
    B, P, E, _ = batch.cells.shape
    V = int(batch.dictv.shape[0])
    b2, e2, v2 = _next_pow2(B), _next_pow2(E), _next_pow2(max(1, V))
    if (b2, e2, v2) == (B, E, V):
        return batch, B
    cells = np.pad(batch.cells, [(0, b2 - B), (0, 0), (0, e2 - E), (0, 0)])
    bmeta = np.pad(batch.bmeta, (0, b2 - B))
    dictv = np.pad(batch.dictv, [(0, v2 - V), (0, 0)])
    str_bytes = np.pad(batch.str_bytes, [(0, v2 - V), (0, 0)])
    return PackedBatch(n=b2, e=e2, cells=cells, bmeta=bmeta,
                       str_bytes=str_bytes, dictv=dictv), B


def pipeline_enabled() -> bool:
    """KTPU_FLATTEN_PIPELINE=0 kill-switch: read dynamically at every use
    site so an operator (or a test monkeypatching os.environ) can drop the
    whole admission/scan path back to the serial dataflow without a
    restart."""
    return featureplane.enabled("KTPU_FLATTEN_PIPELINE")


@dataclass
class PackedRow:
    """One resource's slice of a PackedBatch, rebased onto a private
    string table — the unit of the flatten-row memo (runtime/resourcecache
    FlattenRowCache). ``cells`` is trimmed to the row's own slot count and
    ``str_bytes``/``dictv`` keep only the rows this resource references,
    so a memoized row costs O(own content), not O(original batch)."""

    cells: np.ndarray       # [P, e_row, 2] uint32, w0 rebased to local ids
    bmeta: int              # uint32 scalar
    str_bytes: np.ndarray   # [v, STR_LEN] uint8 (may be empty)
    dictv: np.ndarray       # [v, 5] uint32

    @property
    def nbytes(self) -> int:
        return self.cells.nbytes + self.str_bytes.nbytes + self.dictv.nbytes


@dataclass
class MemoRow:
    """Epoch-keyed flatten-row memo entry: a PackedRow plus the dictionary
    coordinates it was flattened at. Rows compiled at epoch *e* over
    ``n_paths`` paths remain spliceable at any epoch *e' >= e* of the same
    lineage because the dictionary only appends — the row is a valid
    prefix, and :func:`refresh_packed_row` flattens just the appended
    paths and concatenates. This is what lets a policy edit keep the
    flatten work for every cached resource instead of evicting it."""

    row: PackedRow
    n_paths: int              # path-dictionary length at flatten time
    epoch: int                # TensorDictionary.epoch at flatten time


class _PathSlice:
    """Minimal tensors view for :func:`flatten_batch`: the appended tail
    of the path dictionary plus the (full, append-only) kind index."""

    __slots__ = ("paths", "kind_index")

    def __init__(self, paths: list[str], kind_index: dict[str, int]):
        self.paths = paths
        self.kind_index = kind_index

    @property
    def n_paths(self) -> int:
        return len(self.paths)


def _extend_row(row: PackedRow, delta: PackedRow) -> PackedRow:
    """Concatenate a row's cells with a delta-flattened tail along the
    path axis, re-interning the delta's private string table into the
    row's (same (bytes, length) key + OR-merge as splice_packed_rows).
    The delta's bmeta wins the kind bits (computed against the current
    kind index) and ORs its host flag — host conditions are per-slot ORs,
    so the union over path subsets equals the full-flatten flag."""
    p0, e0 = int(row.cells.shape[0]), int(row.cells.shape[1])
    p1, e1 = int(delta.cells.shape[0]), int(delta.cells.shape[1])
    E = max(e0, e1)
    cells = np.zeros((p0 + p1, E, 2), dtype=np.uint32)
    cells[:p0, :e0] = row.cells

    index: dict[tuple[bytes, int], int] = {}
    v0 = int(row.dictv.shape[0])
    sb_rows = [row.str_bytes[i] for i in range(v0)]
    dv_rows = [row.dictv[i].copy() for i in range(v0)]
    for i in range(v0):
        index[(row.str_bytes[i].tobytes(), int(row.dictv[i, 4] & 0x7F))] = i
    v1 = int(delta.dictv.shape[0])
    lut = np.zeros(v1 + 1, dtype=np.uint32)
    for i in range(v1):
        key = (delta.str_bytes[i].tobytes(), int(delta.dictv[i, 4] & 0x7F))
        j = index.get(key)
        if j is None:
            j = len(sb_rows)
            index[key] = j
            sb_rows.append(delta.str_bytes[i])
            dv_rows.append(delta.dictv[i].copy())
        else:
            dv_rows[j] |= delta.dictv[i]
        lut[i + 1] = j + 1
    cells[p0:, :e1, 0] = lut[delta.cells[..., 0]]
    cells[p0:, :e1, 1] = delta.cells[..., 1]

    old_host = (row.bmeta >> 16) & 1
    old_live = (row.bmeta >> 17) & 1
    bmeta = int((delta.bmeta & 0x1FFFF) | ((old_host | old_live << 1) << 16))
    if sb_rows:
        str_bytes = np.stack(sb_rows).astype(np.uint8)
        dictv = np.stack(dv_rows).astype(np.uint32)
    else:
        str_bytes = np.zeros((0, STR_LEN), dtype=np.uint8)
        dictv = np.zeros((0, 5), dtype=np.uint32)
    return PackedRow(cells=np.ascontiguousarray(cells), bmeta=bmeta,
                     str_bytes=str_bytes, dictv=dictv)


def flatten_one_row(resource: dict, tensors, request: dict | None = None,
                    max_slots: int = 16) -> PackedRow:
    """Flatten one resource against ``tensors`` (any object with paths /
    kind_index / n_paths) straight to a PackedRow — the pure-Python
    single-row path used by memo refresh and the delta scanner."""
    fb = flatten_batch([resource], tensors, max_slots=max_slots,
                       requests=[request] if request is not None else None)
    cells, bmeta, str_bytes, dictv = pack_batch(fb)
    return split_packed_rows(PackedBatch(
        n=1, e=fb.e, cells=cells, bmeta=bmeta,
        str_bytes=str_bytes, dictv=dictv))[0]


def refresh_packed_row(memo: MemoRow, resource: dict,
                       tensors: PolicyTensors,
                       request: dict | None = None) -> tuple[MemoRow | None, bool]:
    """Revalidate a memoized flatten row against the current tensor set
    of its lineage. Returns ``(memo_row, extended)``:

    - exact epoch/path match -> the memo unchanged, ``extended=False``;
    - dictionary appended since the row was cut -> flatten only the
      appended paths, concatenate, recompute the kind bits against the
      current kind index, return the refreshed entry with
      ``extended=True`` (still a survival — the per-path work for the old
      prefix was not redone);
    - the memo is from a *longer* dictionary (foreign lineage, or a
      lineage reset) -> ``(None, False)``: caller re-flattens."""
    n_new = tensors.n_paths
    if memo.epoch == tensors.dict_epoch and memo.n_paths == n_new:
        return memo, False
    if memo.n_paths > n_new:
        return None, False
    row = memo.row
    if n_new > memo.n_paths:
        delta = flatten_one_row(
            resource,
            _PathSlice(tensors.paths[memo.n_paths:], tensors.kind_index),
            request=request)
        row = _extend_row(row, delta)
    else:
        # only the kind index appended: recompute the kind bits (the id
        # of a previously-unknown kind may exist now); host/live keep
        kind = (resource.get("kind") or "") if isinstance(resource, dict) else ""
        kid = tensors.kind_index.get(kind, -1)
        bmeta = int((row.bmeta & ~np.uint32(0xFFFF)) | np.uint32(kid + 1))
        row = PackedRow(cells=row.cells, bmeta=bmeta,
                        str_bytes=row.str_bytes, dictv=row.dictv)
    return MemoRow(row=row, n_paths=n_new, epoch=tensors.dict_epoch), True


def split_packed_rows(batch: PackedBatch) -> list[PackedRow]:
    """Decompose a freshly-flattened PackedBatch into per-resource rows.

    Per row the trailing all-zero slot columns are trimmed (zero fill is
    the dead encoding, so they are pure padding) and word0 string ids are
    rebased through a per-row LUT onto a compact private table. The
    inverse is splice_packed_rows; split→splice of every row reproduces
    the batch's verdicts exactly (dictionary value lanes are pure
    functions of the interned string and class-gated on read, so the
    re-merged table can only differ in lanes the kernels never read)."""
    from ..runtime import tracing

    _t0 = time.perf_counter()
    cells, bmeta = np.asarray(batch.cells), np.asarray(batch.bmeta)
    str_bytes, dictv = np.asarray(batch.str_bytes), np.asarray(batch.dictv)
    rows: list[PackedRow] = []
    for b in range(int(batch.n)):
        rc = cells[b]                             # [P, E, 2]
        used = rc.any(axis=2).any(axis=0)         # [E] slot columns in use
        e_row = int(np.max(np.nonzero(used)[0]) + 1) if used.any() else 0
        rc = rc[:, :e_row, :]
        w0 = rc[..., 0]
        ids = np.unique(w0)
        ids = (ids[ids > 0] - 1).astype(np.int64)
        lut = np.zeros(int(dictv.shape[0]) + 1, dtype=np.uint32)
        lut[ids + 1] = np.arange(1, len(ids) + 1, dtype=np.uint32)
        rc = np.stack([lut[w0], rc[..., 1]], axis=-1)
        rows.append(PackedRow(
            cells=np.ascontiguousarray(rc),
            bmeta=int(bmeta[b]),
            str_bytes=np.ascontiguousarray(str_bytes[ids]),
            dictv=np.ascontiguousarray(dictv[ids]),
        ))
    tracing.recorder().add_span(
        tracing.current(), "row_split", _t0, time.perf_counter(),
        rows=len(rows))
    return rows


def splice_packed_rows(rows: list[PackedRow]) -> PackedBatch:
    """Reassemble memoized PackedRows into one PackedBatch: re-intern each
    row's private string table into a shared batch table and remap word0
    through the resulting LUT. Strings are keyed by (padded bytes, length)
    — the length disambiguates texts whose UTF-8 ends in NUL bytes —
    and duplicate dictionary rows merge by elementwise OR, which is exact
    because value lanes are pure functions of the string (lanes set by two
    rows agree; lanes set by neither stay zero)."""
    from ..runtime import tracing

    _t0 = time.perf_counter()
    B = len(rows)
    P = int(rows[0].cells.shape[0]) if B else 0
    E = max([int(r.cells.shape[1]) for r in rows], default=0)
    E = max(E, 1)
    index: dict[tuple[bytes, int], int] = {}
    sb_rows: list[np.ndarray] = []
    dv_rows: list[np.ndarray] = []
    cells = np.zeros((B, P, E, 2), dtype=np.uint32)
    bmeta = np.zeros(B, dtype=np.uint32)
    for b, row in enumerate(rows):
        v = int(row.dictv.shape[0])
        lut = np.zeros(v + 1, dtype=np.uint32)
        for i in range(v):
            key = (row.str_bytes[i].tobytes(), int(row.dictv[i, 4] & 0x7F))
            j = index.get(key)
            if j is None:
                j = len(sb_rows)
                index[key] = j
                sb_rows.append(row.str_bytes[i])
                dv_rows.append(row.dictv[i].copy())
            else:
                dv_rows[j] |= row.dictv[i]
            lut[i + 1] = j + 1
        e_row = int(row.cells.shape[1])
        cells[b, :, :e_row, 0] = lut[row.cells[..., 0]]
        cells[b, :, :e_row, 1] = row.cells[..., 1]
        bmeta[b] = row.bmeta
    V = len(sb_rows)
    if V:
        str_bytes = np.stack(sb_rows).astype(np.uint8)
        dictv = np.stack(dv_rows).astype(np.uint32)
    else:
        str_bytes = np.zeros((1, STR_LEN), dtype=np.uint8)
        dictv = np.zeros((1, 5), dtype=np.uint32)
    tracing.recorder().add_span(
        tracing.current(), "row_splice", _t0, time.perf_counter(), rows=B)
    return PackedBatch(n=B, e=E, cells=cells, bmeta=bmeta,
                       str_bytes=str_bytes, dictv=dictv)


def merge_packed(chunks: list[PackedBatch]) -> PackedBatch:
    """Concatenate independently-flattened PackedBatches (the chunked
    multi-worker native flatten) into one batch: slot axes pad up to the
    widest chunk and the per-chunk string tables re-intern into a shared
    one with the same (bytes, length) key and OR-merge as
    splice_packed_rows."""
    if len(chunks) == 1:
        return chunks[0]
    B = sum(int(c.n) for c in chunks)
    P = int(chunks[0].cells.shape[1])
    E = max(1, max(int(c.e) for c in chunks))
    cells = np.zeros((B, P, E, 2), dtype=np.uint32)
    bmeta = np.zeros(B, dtype=np.uint32)
    index: dict[tuple[bytes, int], int] = {}
    sb_rows: list[np.ndarray] = []
    dv_rows: list[np.ndarray] = []
    at = 0
    for c in chunks:
        c_sb, c_dv = np.asarray(c.str_bytes), np.asarray(c.dictv)
        v = int(c_dv.shape[0])
        lut = np.zeros(v + 1, dtype=np.uint32)
        for i in range(v):
            key = (c_sb[i].tobytes(), int(c_dv[i, 4] & 0x7F))
            j = index.get(key)
            if j is None:
                j = len(sb_rows)
                index[key] = j
                sb_rows.append(c_sb[i])
                dv_rows.append(c_dv[i].copy())
            else:
                dv_rows[j] |= c_dv[i]
            lut[i + 1] = j + 1
        cc = np.asarray(c.cells)
        n, e = int(c.n), int(cc.shape[2])
        cells[at:at + n, :, :e, 0] = lut[cc[:n, :, :, 0]]
        cells[at:at + n, :, :e, 1] = cc[:n, :, :, 1]
        bmeta[at:at + n] = np.asarray(c.bmeta)[:n]
        at += n
    str_bytes = np.stack(sb_rows).astype(np.uint8) if sb_rows else \
        np.zeros((1, STR_LEN), dtype=np.uint8)
    dictv = np.stack(dv_rows).astype(np.uint32) if dv_rows else \
        np.zeros((1, 5), dtype=np.uint32)
    return PackedBatch(n=B, e=E, cells=cells, bmeta=bmeta,
                       str_bytes=str_bytes, dictv=dictv)


# ---------------------------------------------------------------- wire codec
#
# Columnar wire format for the streaming admission plane
# (runtime/stream_server.py): clients ship pre-tokenized rows/blocks in
# the packed transfer layout so the server splices them device-ready
# without re-parsing JSON or re-walking the resource. All integers are
# little-endian; arrays travel as raw C-contiguous buffers in the same
# dtypes the device kernels consume.

_ROW_HDR = struct.Struct("<IIII")      # P, e_row, v, bmeta
_BLOCK_HDR = struct.Struct("<IIII")    # B, P, E, V


def encode_packed_row(row: PackedRow) -> bytes:
    """Serialize one PackedRow for the stream wire. Inverse of
    :func:`decode_packed_row`; round-trips bit-exactly."""
    p, e = (int(row.cells.shape[0]), int(row.cells.shape[1]))
    v = int(row.dictv.shape[0])
    return b"".join((
        _ROW_HDR.pack(p, e, v, int(row.bmeta) & 0xFFFFFFFF),
        np.ascontiguousarray(row.cells, dtype="<u4").tobytes(),
        np.ascontiguousarray(row.str_bytes, dtype=np.uint8).tobytes(),
        np.ascontiguousarray(row.dictv, dtype="<u4").tobytes(),
    ))


def decode_packed_row(buf, offset: int = 0) -> tuple[PackedRow, int]:
    """Deserialize one PackedRow; returns ``(row, next_offset)``. The
    arrays view the input buffer (zero-copy, read-only) — every consumer
    (splice, graft) only reads them."""
    p, e, v, bmeta = _ROW_HDR.unpack_from(buf, offset)
    o = offset + _ROW_HDR.size
    cells = np.frombuffer(buf, "<u4", p * e * 2, o).reshape(p, e, 2)
    o += p * e * 2 * 4
    str_bytes = np.frombuffer(buf, np.uint8, v * STR_LEN, o).reshape(
        v, STR_LEN)
    o += v * STR_LEN
    dictv = np.frombuffer(buf, "<u4", v * 5, o).reshape(v, 5)
    o += v * 5 * 4
    return PackedRow(cells=cells, bmeta=int(bmeta), str_bytes=str_bytes,
                     dictv=dictv), o


def encode_packed_block(batch: PackedBatch) -> bytes:
    """Serialize a whole pre-spliced PackedBatch (the zero-re-intern wire
    granularity: the server pads and dispatches it without touching the
    string table)."""
    B, P, E = (int(batch.cells.shape[0]), int(batch.cells.shape[1]),
               int(batch.cells.shape[2]))
    V = int(batch.dictv.shape[0])
    return b"".join((
        _BLOCK_HDR.pack(B, P, E, V),
        np.ascontiguousarray(batch.cells, dtype="<u4").tobytes(),
        np.ascontiguousarray(batch.bmeta, dtype="<u4").tobytes(),
        np.ascontiguousarray(batch.str_bytes, dtype=np.uint8).tobytes(),
        np.ascontiguousarray(batch.dictv, dtype="<u4").tobytes(),
    ))


def decode_packed_block(buf, offset: int = 0) -> tuple[PackedBatch, int]:
    """Inverse of :func:`encode_packed_block`; zero-copy read-only views."""
    B, P, E, V = _BLOCK_HDR.unpack_from(buf, offset)
    o = offset + _BLOCK_HDR.size
    cells = np.frombuffer(buf, "<u4", B * P * E * 2, o).reshape(B, P, E, 2)
    o += B * P * E * 2 * 4
    bmeta = np.frombuffer(buf, "<u4", B, o)
    o += B * 4
    str_bytes = np.frombuffer(buf, np.uint8, V * STR_LEN, o).reshape(
        V, STR_LEN)
    o += V * STR_LEN
    dictv = np.frombuffer(buf, "<u4", V * 5, o).reshape(V, 5)
    o += V * 5 * 4
    return PackedBatch(n=B, e=E, cells=cells, bmeta=bmeta,
                       str_bytes=str_bytes, dictv=dictv), o


def grow_dict_headroom(batch: PackedBatch,
                       min_free: int = 1) -> PackedBatch:
    """Pad the string table to the next power of two that leaves at
    least ``min_free`` unused rows past the current table size — the
    headroom continuous batching needs so a late-joining row whose
    strings aren't all interned yet can still graft. Zero rows are the
    natural dead encoding (same fill pad_to_buckets_packed uses), so
    the extra slots are invisible to the kernels."""
    from dataclasses import replace

    v = int(batch.dictv.shape[0])
    target = _next_pow2(v + max(1, min_free))
    if target == v:
        return batch
    return replace(
        batch,
        dictv=np.pad(batch.dictv, [(0, target - v), (0, 0)]),
        str_bytes=np.pad(batch.str_bytes, [(0, target - v), (0, 0)]))


def graft_packed_rows(batch: PackedBatch, rows: list[PackedRow],
                      at: int, v_used: int) -> int:
    """Continuous-batching late-join: write ``rows`` into the padding
    slots of an already-padded batch, in place, starting at row ``at``.

    Safe only because padded row slots are fresh zero fill (np.pad always
    copies) and the batch is flush-private. Each row's private string
    table re-interns into the batch dictionary with the same
    (bytes, length) key + elementwise OR-merge as splice_packed_rows
    (exact: value lanes are pure functions of the interned string);
    strings the batch doesn't know yet take free dictionary rows above
    ``v_used`` — the live table size before bucket padding.

    Returns how many leading rows were grafted; stops at the first row
    that doesn't fit (slot width, path count, or dictionary capacity) so
    the caller re-queues the rest in arrival order. Must be called
    before the batch's blob/flat caches materialize."""
    cells = batch.cells
    B, P, E = int(cells.shape[0]), int(cells.shape[1]), int(cells.shape[2])
    V = int(batch.dictv.shape[0])
    index = getattr(batch, "_graft_index", None)
    if index is None:
        index = {}
        for i in range(v_used):
            index[(batch.str_bytes[i].tobytes(),
                   int(batch.dictv[i, 4] & 0x7F))] = i
        object.__setattr__(batch, "_graft_index", index)
    else:
        v_used = getattr(batch, "_graft_vused", v_used)
    grafted = 0
    for row in rows:
        b = at + grafted
        if b >= B:
            break
        p, e_row = int(row.cells.shape[0]), int(row.cells.shape[1])
        if p != P or e_row > E:
            break
        # two-phase intern: count the new strings first so a row that
        # overflows the dictionary leaves the batch untouched
        v = int(row.dictv.shape[0])
        keys = [(row.str_bytes[i].tobytes(), int(row.dictv[i, 4] & 0x7F))
                for i in range(v)]
        fresh = [k for k in keys if k not in index]
        # dict.fromkeys: a row may reference the same new string twice
        fresh = list(dict.fromkeys(fresh))
        if v_used + len(fresh) > V:
            break
        lut = np.zeros(v + 1, dtype=np.uint32)
        for i, key in enumerate(keys):
            j = index.get(key)
            if j is None:
                j = v_used
                index[key] = j
                batch.str_bytes[j] = row.str_bytes[i]
                batch.dictv[j] = row.dictv[i]
                v_used += 1
            else:
                batch.dictv[j] |= row.dictv[i]
            lut[i + 1] = j + 1
        cells[b, :, :e_row, 0] = lut[row.cells[..., 0]]
        cells[b, :, :e_row, 1] = row.cells[..., 1]
        batch.bmeta[b] = np.uint32(int(row.bmeta) & 0xFFFFFFFF)
        grafted += 1
    object.__setattr__(batch, "_graft_vused", v_used)
    # any lazily-built views of the pre-graft content are now stale
    for attr in ("_blob", "_flat", "_strings", "_packed"):
        if getattr(batch, attr, None) is not None:
            object.__delattr__(batch, attr)
    return grafted


class _Interner:
    def __init__(self):
        self.index: dict[str, int] = {}
        self.strings: list[str] = []

    def intern(self, s: str) -> int:
        i = self.index.get(s)
        if i is None:
            i = len(self.strings)
            self.index[s] = i
            self.strings.append(s)
        return i


def _value_to_micro(value) -> int | None:
    try:
        if isinstance(value, bool):
            return None
        if isinstance(value, float):
            # decode the shortest decimal repr (the JSON token) rather than
            # the exact binary double: "0.1" means 100000 micro, and repr
            # artifacts like 0.30000000000000004 take the host lane — the
            # same decision the native flattener makes from the token text
            micro = parse_quantity(repr(value)) * NUM_SCALE
        elif isinstance(value, int):
            from fractions import Fraction

            micro = Fraction(value) * NUM_SCALE
        elif isinstance(value, str):
            micro = parse_quantity(value) * NUM_SCALE
        else:
            return None
    except (QuantityError, ValueError, OverflowError):
        return None
    if micro.denominator != 1 or abs(micro.numerator) > NUM_MAX:
        return None
    return int(micro)


def _digit_capped(s: str) -> bool:
    """True when the leading number part has more than 36 digits — beyond
    the native flattener's exact __int128 range. Mirrors the counting loop
    in ktpu_flatten.cpp quantity_to_micro: ASCII-trim, optional sign, then
    digits with a single embedded dot."""
    s = s.strip(" \t\n\r\f\v")
    i = 0
    if i < len(s) and s[i] in "+-":
        i += 1
    n = 0
    seen_dot = False
    for ch in s[i:]:
        if "0" <= ch <= "9":
            n += 1
            if n > 36:
                return True
        elif ch == "." and not seen_dot:
            seen_dot = True
        else:
            break
    return False


def _needs_host_parse(s: str) -> bool:
    """True when the string could parse differently under unicode-aware
    rules (str.strip(), regex \\d, float()) than under the ASCII grammar
    the device lanes and the native flattener implement: any unicode
    whitespace/decimal digit, or the \\x1c-\\x1f controls str.isspace()
    accepts. Such leaves route the resource to the CPU oracle."""
    import unicodedata

    for ch in s:
        o = ord(ch)
        if 0x1C <= o <= 0x1F:
            return True
        if o > 0x7F and (ch.isspace() or unicodedata.category(ch) == "Nd"):
            return True
    return False


def _duration_micro(value: str) -> int | None:
    """Go-duration parse -> micro-seconds. ``dur_ok`` (strict) additionally
    excludes the literal "0" (operator.go:82 parseDuration); ``dur_any``
    keeps it (duration.go's deprecated Duration* handlers accept it)."""
    try:
        secs = parse_duration(value)
    except DurationError:
        return None
    micro = round(secs * 1_000_000)
    if abs(micro) > NUM_MAX:
        return None
    return micro


def _effective_namespace(resource: dict) -> str:
    meta = resource.get("metadata") or {}
    if resource.get("kind") == "Namespace":
        return meta.get("name") or ""
    return meta.get("namespace") or ""


def _enumerate_slots(resource, segments: list[str], request: dict,
                     ns_eff: str):
    """Yield (mask, elem0, leaf_value_or_None, leaf_present, null_break)
    for every chain of ``segments`` through the resource (or the request
    envelope / the effective-namespace synthetic). A phantom slot (leaf None
    + short mask) marks a broken chain; ``null_break`` records that the
    break happened at a node that exists but is not a map — the JMESPath
    fork resolves such a path to null instead of raising NotFound
    (interpreter._field), which conditions treat as a null key, not an
    unresolved variable. Empty arrays yield nothing."""
    if segments and segments[0] == NSEFF_MARK:
        return [(0b11, -1, ns_eff, True, False)]
    if segments and segments[0] == REQ_MARK:
        root = request
        segments = segments[1:]
        base_mask = 0b11 if request else 0b1
        if not segments:
            return [(base_mask, -1, None, False, False)]
        offset = 1
    else:
        root = resource
        base_mask = 0b1
        offset = 0

    out = []

    def walk(node, i: int, mask: int, elem0: int):
        if i == len(segments):
            out.append((mask, elem0, node, True, False))
            return
        seg = segments[i]
        bit = 1 << (i + 1 + offset)
        if seg == "*":
            if not isinstance(node, list):
                # a list pattern over an existing non-list node is a
                # structural mismatch (validateResourceElement array case)
                out.append((mask, elem0, None, False, True))
                return
            for idx, el in enumerate(node):
                walk(el, i + 1, mask | bit, idx if elem0 < 0 else elem0)
        else:
            if not isinstance(node, dict):
                out.append((mask, elem0, None, False, True))
                return
            if seg not in node:
                out.append((mask, elem0, None, False, False))
                return
            walk(node[seg], i + 1, mask | bit, elem0)

    if root is None or (offset == 1 and not request):
        return [(base_mask, -1, None, False, False)]
    walk(root, 0, base_mask, -1)  # bit 0: the root itself
    return out


def flatten_batch(resources: list[dict], tensors: PolicyTensors,
                  max_slots: int = 16,
                  requests: list[dict] | None = None) -> FlatBatch:
    """``requests`` optionally supplies per-resource admission envelopes
    (operation/namespace/userInfo) backing REQ_MARK paths; a background
    scan passes none and request.* condition keys resolve as absent, the
    same way the oracle's scan context leaves them unresolved."""
    B, P = len(resources), tensors.n_paths
    path_segments = [p.split(SEP) for p in tensors.paths]
    envelopes = requests if requests is not None else [{}] * B

    # first pass: find E
    all_slots: list[list] = []
    e_needed = 1
    host_flag = np.zeros(B, dtype=bool)
    for b, resource in enumerate(resources):
        row = []
        ns_eff = _effective_namespace(resource) if isinstance(resource, dict) else ""
        env = envelopes[b] or {}
        for segs in path_segments:
            slots = _enumerate_slots(resource, segs, env, ns_eff)
            if len(slots) > max_slots:
                host_flag[b] = True
                slots = slots[:max_slots]
            e_needed = max(e_needed, len(slots))
            row.append(slots)
        all_slots.append(row)
    E = e_needed

    interner = _Interner()
    mask = np.zeros((B, P, E), dtype=np.uint16)
    slot_valid = np.zeros((B, P, E), dtype=bool)
    null_break = np.zeros((B, P, E), dtype=bool)
    type_tag = np.full((B, P, E), T_ABSENT, dtype=np.int8)
    str_id = np.full((B, P, E), -1, dtype=np.int32)
    num_val = np.zeros((B, P, E), dtype=np.int64)
    num_ok = np.zeros((B, P, E), dtype=bool)
    num_plain = np.zeros((B, P, E), dtype=bool)
    num_int = np.zeros((B, P, E), dtype=bool)
    dur_val = np.zeros((B, P, E), dtype=np.int64)
    dur_ok = np.zeros((B, P, E), dtype=bool)
    dur_any = np.zeros((B, P, E), dtype=bool)
    bool_val = np.zeros((B, P, E), dtype=bool)
    elem0 = np.full((B, P, E), -1, dtype=np.int32)
    kind_id = np.full(B, -1, dtype=np.int32)

    for b, resource in enumerate(resources):
        kind = (resource.get("kind") or "") if isinstance(resource, dict) else ""
        kind_id[b] = tensors.kind_index.get(kind, -1)
        for p in range(P):
            for e, (m, e0, value, leaf, nbrk) in enumerate(all_slots[b][p]):
                mask[b, p, e] = m
                slot_valid[b, p, e] = True
                null_break[b, p, e] = nbrk
                elem0[b, p, e] = e0
                if not leaf:
                    continue
                if value is None:
                    type_tag[b, p, e] = T_NULL
                elif isinstance(value, bool):
                    type_tag[b, p, e] = T_BOOL
                    bool_val[b, p, e] = value
                    str_id[b, p, e] = interner.intern("true" if value else "false")
                elif isinstance(value, (int, float)):
                    type_tag[b, p, e] = T_NUM
                    num_int[b, p, e] = isinstance(value, int)
                    s = value_to_string_for_equality(value)
                    if len(s) <= STR_LEN:
                        str_id[b, p, e] = interner.intern(s)
                    n = _value_to_micro(value)
                    if n is not None:
                        num_val[b, p, e] = n
                        num_ok[b, p, e] = True
                        num_plain[b, p, e] = True
                    else:
                        host_flag[b] = True
                elif isinstance(value, str):
                    type_tag[b, p, e] = T_STR
                    if len(value.encode("utf-8")) <= STR_LEN:
                        str_id[b, p, e] = interner.intern(value)
                    else:
                        host_flag[b] = True
                    if _needs_host_parse(value):
                        # unicode-sensitive parse: leave the numeric lanes
                        # empty and let the oracle evaluate this resource
                        host_flag[b] = True
                        continue
                    if _digit_capped(value):
                        # >36-digit number part: exact range exceeded
                        host_flag[b] = True
                        continue
                    try:
                        int(value, 10)
                        num_int[b, p, e] = True  # strconv.ParseInt-able
                    except ValueError:
                        pass
                    n = _value_to_micro(value)
                    if n is not None:
                        num_val[b, p, e] = n
                        num_ok[b, p, e] = True
                        try:
                            float(value)
                            num_plain[b, p, e] = True
                        except ValueError:
                            pass
                    d = _duration_micro(value)
                    if d is not None:
                        dur_val[b, p, e] = d
                        dur_any[b, p, e] = True
                        dur_ok[b, p, e] = value != "0"
                elif isinstance(value, dict):
                    type_tag[b, p, e] = T_OBJ
                else:
                    type_tag[b, p, e] = T_LIST

    num_hi = (num_val >> 31).astype(np.int32)
    num_lo = (num_val & 0x7FFFFFFF).astype(np.int32)
    dur_hi = (dur_val >> 31).astype(np.int32)
    dur_lo = (dur_val & 0x7FFFFFFF).astype(np.int32)

    V = max(1, len(interner.strings))
    str_bytes = np.zeros((V, STR_LEN), dtype=np.uint8)
    str_len = np.zeros(V, dtype=np.int32)
    str_has_glob = np.zeros(V, dtype=bool)
    for i, s in enumerate(interner.strings):
        bs = s.encode("utf-8")[:STR_LEN]
        str_bytes[i, : len(bs)] = np.frombuffer(bs, dtype=np.uint8)
        str_len[i] = len(bs)
        str_has_glob[i] = "*" in s or "?" in s

    return FlatBatch(
        n=B, e=E, mask=mask, slot_valid=slot_valid, null_break=null_break,
        type_tag=type_tag,
        str_id=str_id, num_val=num_val, num_hi=num_hi, num_lo=num_lo,
        num_ok=num_ok, num_plain=num_plain, num_int=num_int,
        dur_hi=dur_hi, dur_lo=dur_lo, dur_ok=dur_ok, dur_any=dur_any,
        bool_val=bool_val,
        elem0=elem0, kind_id=kind_id, host_flag=host_flag,
        live=np.ones(B, dtype=bool),
        str_bytes=str_bytes, str_len=str_len, str_has_glob=str_has_glob,
        strings=interner.strings,
    )
