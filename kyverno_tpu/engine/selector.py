"""Kubernetes LabelSelector evaluation (metav1.LabelSelectorAsSelector).

matchLabels is ANDed with matchExpressions; supported operators are
In, NotIn, Exists, DoesNotExist. Used by the match/exclude filters
(/root/reference/pkg/engine/utils.go:100 checkSelector).
"""

from __future__ import annotations


class SelectorError(ValueError):
    pass


def selector_matches(selector: dict, labels: dict) -> bool:
    """Evaluate a LabelSelector JSON object against a label map."""
    if selector is None:
        return False
    labels = labels or {}
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key = expr.get("key", "")
        op = expr.get("operator", "")
        values = expr.get("values") or []
        if op == "In":
            if not values:
                raise SelectorError("In operator requires values")
            if labels.get(key) not in values:
                return False
        elif op == "NotIn":
            if not values:
                raise SelectorError("NotIn operator requires values")
            if key in labels and labels[key] in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:
            raise SelectorError(f"unknown selector operator: {op!r}")
    return True
