"""Mutation driver: iterate mutate rules, chain the patched resource through
rules, re-injecting it into the JSON context so later rules and variables
see earlier patches.

Mirrors /root/reference/pkg/engine/mutation.go (Mutate:31,
mutateForEachResource:128, mutateResource:201).
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field

from .json_context_loader import load_context
from .match import matches_resource_description
from .mutate.handlers import MutateResult, apply_mutation
from .policy_context import PolicyContext
from .response import (
    EngineResponse,
    PolicyResponse,
    PolicySpecSummary,
    ResourceSpec,
    RuleResponse,
    RuleStatus,
    RuleType,
)
from .validation import (
    _add_element_to_context,
    check_preconditions,
    evaluate_list,
    rule_error,
    rule_response,
)
from .variables import VariableResolutionError, substitute_all


@dataclass
class _MutateOutcome:
    skip: bool = False
    patched_resource: dict | None = None
    patches: list = field(default_factory=list)
    message: str = ""


def mutate(policy_ctx: PolicyContext) -> EngineResponse:
    """mutation.go:31 Mutate."""
    start = time.monotonic()
    resp = EngineResponse(policy_response=PolicyResponse())
    policy = policy_ctx.policy
    patched_resource = policy_ctx.new_resource
    ctx = policy_ctx.json_context

    _start_mutate_response(resp, policy, patched_resource)

    ctx.checkpoint()
    try:
        for rule in policy.spec.rules:
            if not rule.has_mutate():
                continue

            ok, _ = matches_resource_description(
                patched_resource,
                rule,
                policy_ctx.admission_info,
                policy_ctx.exclude_group_role,
                policy_ctx.namespace_labels,
                policy.namespace,
            )
            if not ok:
                continue

            # Reset() drops externally-loaded context but the patched
            # resource must survive for rule chaining (mutation.go:71-80)
            try:
                resource = ctx.query("request.object")
            except Exception:
                resource = None
            ctx.reset()
            if isinstance(resource, dict):
                ctx.add_resource(resource)

            try:
                load_context(rule.context, policy_ctx, rule.name)
            except Exception:
                continue  # mutation.go:82-89: context failure skips the rule

            if rule.mutation.foreach:
                rule_resp, patched_resource = _mutate_foreach(
                    rule, policy_ctx, patched_resource
                )
            else:
                rule_resp, patched_resource = _run_mutate_rule(
                    rule, policy_ctx, patched_resource, 0
                )

            if rule_resp is not None:
                resp.policy_response.rules.append(rule_resp)
                if rule_resp.status is RuleStatus.ERROR:
                    resp.policy_response.rules_error_count += 1
                else:
                    resp.policy_response.rules_applied_count += 1
    finally:
        ctx.restore()

    resp.patched_resource = patched_resource
    resp.policy_response.processing_time_s = time.monotonic() - start
    return resp


def _run_mutate_rule(rule, policy_ctx, resource, foreach_index):
    """The single-rule wrapper around mutateResource (mutation.go:96-113)."""
    outcome, err = _mutate_resource(rule, policy_ctx, resource, foreach_index)
    if err is not None:
        status = RuleStatus.SKIP if outcome.skip else RuleStatus.ERROR
        return rule_response(rule, RuleType.MUTATION, str(err), status), resource
    message = outcome.message or "mutated resource"
    rr = rule_response(rule, RuleType.MUTATION, message, RuleStatus.PASS)
    rr.patches = outcome.patches
    return rr, outcome.patched_resource


def _mutate_foreach(rule, policy_ctx: PolicyContext, resource: dict):
    """mutation.go:128 mutateForEachResource."""
    ctx = policy_ctx.json_context
    apply_count = 0
    patched_resource = resource
    all_patches: list = []

    for foreach_index, foreach in enumerate(rule.mutation.foreach):
        try:
            load_context(foreach.context, policy_ctx, rule.name)
        except Exception as e:
            return (
                rule_error(rule, RuleType.MUTATION, "failed to load context", e),
                resource,
            )

        try:
            preconditions_passed = check_preconditions(policy_ctx, foreach.preconditions)
        except Exception as e:
            return (
                rule_error(rule, RuleType.MUTATION, "failed to evaluate preconditions", e),
                resource,
            )
        if not preconditions_passed:
            return (
                rule_response(
                    rule, RuleType.MUTATION, "preconditions not met", RuleStatus.SKIP
                ),
                resource,
            )

        try:
            elements = evaluate_list(foreach.list_expr, ctx)
        except Exception as e:
            return (
                rule_error(
                    rule, RuleType.MUTATION, f"failed to evaluate list {foreach.list_expr}", e
                ),
                resource,
            )

        ctx.checkpoint()
        try:
            for element in elements:
                ctx.reset()
                element_ctx = policy_ctx.copy()
                try:
                    _add_element_to_context(element_ctx, element)
                except Exception as e:
                    return (
                        rule_error(rule, RuleType.MUTATION, "failed to process foreach", e),
                        resource,
                    )
                outcome, err = _mutate_resource(
                    rule, element_ctx, patched_resource, foreach_index
                )
                if err is not None:
                    if outcome.skip:
                        continue  # element not matched / preconditions miss
                    return (
                        rule_response(rule, RuleType.MUTATION, str(err), RuleStatus.ERROR),
                        resource,
                    )
                if outcome.patched_resource is not None:
                    patched_resource = outcome.patched_resource
                all_patches.extend(outcome.patches)
                apply_count += 1
        finally:
            ctx.restore()

    if apply_count == 0:
        return (
            rule_response(rule, RuleType.MUTATION, "0 elements processed", RuleStatus.SKIP),
            resource,
        )
    rr = rule_response(
        rule, RuleType.MUTATION, f"{apply_count} elements processed", RuleStatus.PASS
    )
    rr.patches = all_patches
    return rr, patched_resource


def _mutate_resource(rule, policy_ctx: PolicyContext, resource: dict, foreach_index: int):
    """mutation.go:201 mutateResource -> (outcome, error-or-None)."""
    ctx = policy_ctx.json_context
    outcome = _MutateOutcome()

    try:
        preconditions_passed = check_preconditions(policy_ctx, rule.preconditions)
    except Exception as e:
        return outcome, e
    if not preconditions_passed:
        outcome.skip = True
        return outcome, Exception("preconditions mismatch")

    try:
        mutation = _substitute_mutation(ctx, rule.mutation, foreach_index)
    except VariableResolutionError as e:
        return outcome, Exception(f"variable substitution failed: {e}")

    result: MutateResult = apply_mutation(mutation, resource, foreach_index)

    if result.status is RuleStatus.PASS:
        # an anchor-gated patch that matched nothing produces no patches:
        # the rule is reported as skipped (mutation.go:231-236)
        if not result.patches:
            outcome.skip = True
            if result.patched_resource is not None:
                ctx.add_resource(result.patched_resource)
            return outcome, Exception("resource does not match pattern")
        outcome.patched_resource = result.patched_resource
        outcome.patches = result.patches
        outcome.message = result.message
    elif result.status is RuleStatus.FAIL:
        return outcome, Exception(result.message)

    if result.patched_resource is not None:
        ctx.add_resource(result.patched_resource)
    return outcome, None


def _substitute_mutation(ctx, mutation, foreach_index: int = 0):
    """variables.SubstituteAllInRule scoped to the mutation block. Only the
    foreach entry selected by ``foreach_index`` is substituted — it is the
    only one apply_mutation will use for this element."""
    substituted = copy.copy(mutation)
    if mutation.patch_strategic_merge is not None:
        substituted.patch_strategic_merge = substitute_all(
            ctx, mutation.patch_strategic_merge
        )
    if mutation.overlay is not None:
        substituted.overlay = substitute_all(ctx, mutation.overlay)
    if mutation.patches:
        substituted.patches = substitute_all(ctx, mutation.patches)
    if mutation.patches_json6902:
        substituted.patches_json6902 = substitute_all(ctx, mutation.patches_json6902)
    if mutation.foreach:
        substituted.foreach = list(mutation.foreach)
        fe = mutation.foreach[foreach_index]
        fe_copy = copy.copy(fe)
        if fe.patch_strategic_merge is not None:
            fe_copy.patch_strategic_merge = substitute_all(ctx, fe.patch_strategic_merge)
        substituted.foreach[foreach_index] = fe_copy
    return substituted


def _start_mutate_response(resp: EngineResponse, policy, resource: dict) -> None:
    meta = (resource or {}).get("metadata") or {}
    resp.policy_response.policy = PolicySpecSummary(
        name=policy.name,
        validation_failure_action=policy.spec.validation_failure_action,
    )
    resp.policy_response.resource = ResourceSpec(
        kind=(resource or {}).get("kind", ""),
        api_version=(resource or {}).get("apiVersion", ""),
        namespace=meta.get("namespace", ""),
        name=meta.get("name", ""),
    )
