"""Precondition / deny condition operators.

Mirrors /root/reference/pkg/engine/variables/operator/: Equals, NotEquals,
In, AnyIn, AllIn, NotIn, AnyNotIn, AllNotIn, GreaterThan(OrEquals),
LessThan(OrEquals), Duration*. Key/value arrive with variables already
substituted. Semantics notes carried over from the reference:

  - string Equals: durations compare first, then k8s quantities, then the
    condition *value* acts as the wildcard pattern over the key
  - In-family with string key: key is the wildcard pattern over list items;
    a plain-string value may be a JSON-encoded array
  - Any/All-In with list keys: wildcard per-element containment
  - numeric compare coerces int/float/duration/quantity from strings
"""

from __future__ import annotations

import json
import math

from ..utils.duration import DurationError, parse_duration
from ..utils.quantity import QuantityError, parse_quantity
from ..utils.wildcard import wildcard_match


def evaluate_condition(key, operator: str, value) -> bool:
    """variables/evaluate.go:11 Evaluate (substitution already applied)."""
    op = (operator or "").lower()
    if op in ("equal", "equals"):
        return _equal(key, value)
    if op in ("notequal", "notequals"):
        return not _equal(key, value)
    if op == "in":
        return _in(key, value)
    if op == "anyin":
        return _any_in(key, value)
    if op == "allin":
        return _all_in(key, value)
    if op == "notin":
        return _not_in(key, value)
    if op == "anynotin":
        return _any_not_in(key, value)
    if op == "allnotin":
        return _all_not_in(key, value)
    if op in ("greaterthanorequals", "greaterthan", "lessthanorequals", "lessthan"):
        return _numeric(key, op, value)
    if op in (
        "durationgreaterthanorequals",
        "durationgreaterthan",
        "durationlessthanorequals",
        "durationlessthan",
    ):
        return _duration_compare(key, op.removeprefix("duration"), value)
    return False  # unsupported operator


def evaluate_conditions(conditions, evaluate=None) -> bool:
    """variables/evaluate.go:21 EvaluateConditions: {any/all} dict or bare
    list (backwards compat). ``evaluate`` defaults to evaluate_condition on
    already-substituted condition dicts."""
    ev = evaluate or (
        lambda c: evaluate_condition(c.get("key"), c.get("operator", ""), c.get("value"))
    )
    if isinstance(conditions, dict):
        any_conds = conditions.get("any")
        all_conds = conditions.get("all")
        any_ok = True
        if any_conds is not None:
            any_ok = any(ev(c) for c in any_conds)
        all_ok = all(ev(c) for c in (all_conds or []))
        return any_ok and all_ok
    if isinstance(conditions, list):
        return all(ev(c) for c in conditions)
    return False


# ---------------------------------------------------------------- duration


def _parse_duration_pair(key, value) -> tuple[float, float] | None:
    """operator.go:82 parseDuration: at least one side must be a real
    duration string (not "0"); the other may be numeric seconds."""

    def as_duration(x) -> float | None:
        if isinstance(x, str) and x != "0":
            try:
                return parse_duration(x)
            except DurationError:
                return None
        return None

    kd, vd = as_duration(key), as_duration(value)
    if kd is None and vd is None:
        return None

    def as_seconds(x) -> float | None:
        if isinstance(x, bool):
            return None
        if isinstance(x, (int, float)):
            return float(x)
        return None

    if kd is None:
        kd = as_seconds(key)
        if kd is None:
            return None
    if vd is None:
        vd = as_seconds(value)
        if vd is None:
            return None
    return kd, vd


def _compare(a: float, b: float, op: str) -> bool:
    if op == "greaterthanorequals":
        return a >= b
    if op == "greaterthan":
        return a > b
    if op == "lessthanorequals":
        return a <= b
    if op == "lessthan":
        return a < b
    if op in ("equal", "equals"):
        return a == b
    if op in ("notequal", "notequals"):
        return a != b
    return False


def _duration_compare(key, op: str, value) -> bool:
    """duration.go: deprecated Duration* handlers; int/float = seconds."""

    def to_seconds(x) -> float | None:
        if isinstance(x, bool):
            return None
        if isinstance(x, (int, float)):
            return float(x)
        if isinstance(x, str):
            try:
                return parse_duration(x)
            except DurationError:
                return None
        return None

    k, v = to_seconds(key), to_seconds(value)
    if k is None or v is None:
        return False
    return _compare(k, v, op)


# ------------------------------------------------------------------- equal


def _equal(key, value) -> bool:
    if isinstance(key, bool):
        return isinstance(value, bool) and key == value
    if isinstance(key, int):
        return _equal_int(key, value)
    if isinstance(key, float):
        return _equal_float(key, value)
    if isinstance(key, str):
        return _equal_string(key, value)
    if isinstance(key, (dict, list)):
        return type(value) is type(key) and key == value
    return False


def _equal_int(key: int, value) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, int):
        return value == key
    if isinstance(value, float):
        return value == math.trunc(value) and int(value) == key
    if isinstance(value, str):
        try:
            return int(value, 10) == key
        except ValueError:
            return False
    return False


def _equal_float(key: float, value) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, int):
        return key == math.trunc(key) and int(key) == value
    if isinstance(value, float):
        return value == key
    if isinstance(value, str):
        try:
            return float(value) == key
        except ValueError:
            return False
    return False


def _equal_string(key: str, value) -> bool:
    pair = _parse_duration_pair(key, value)
    if pair is not None:
        return pair[0] == pair[1]
    try:
        kq = parse_quantity(key)
        if isinstance(value, str):
            try:
                return kq == parse_quantity(value)
            except QuantityError:
                return False
    except QuantityError:
        pass
    if isinstance(value, str):
        return wildcard_match(value, key)  # the condition value is the pattern
    return False


# ---------------------------------------------------------------- in-family
#
# Reference quirks carried over deliberately (in.go / anyin.go / allin.go /
# notin.go / anynotin.go / allnotin.go):
#   - numeric keys Sprint-coerce to strings for In/NotIn/AnyIn/AnyNotIn/
#     AllNotIn, but NOT for AllIn (allin.go has no numeric branch)
#   - a single-element list key equal to a plain-string value short-circuits
#     to "exists" BEFORE the not-in flag applies, so NotIn(['a'], 'a') is true
#   - In/NotIn require string elements in a list value; the Any/All family
#     Sprint-coerces them
#   - In/NotIn set containment is exact; Any/All families use wildcards


def _sprint(v) -> str:
    """Go fmt.Sprint for the value kinds that appear in conditions."""
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "<nil>"
    if isinstance(v, float) and v == math.trunc(v) and abs(v) < 1e21:
        return str(int(v))  # Go %v prints 5.0 as "5"
    return str(v)


def _as_string_slice(key, coerce: bool) -> list[str] | None:
    if not isinstance(key, list):
        return None
    out = []
    for el in key:
        if isinstance(el, str):
            out.append(el)
        elif coerce:
            out.append(_sprint(el))
        else:
            return None  # reference panics; we fail the condition
    return out


def _key_exists_in_array(key: str, value) -> tuple[bool, bool]:
    """in.go:62 keyExistsInArray -> (invalid_type, exists)."""
    if isinstance(value, list):
        for val in value:
            if wildcard_match(key, _sprint(val)):
                return False, True
        return False, False
    if isinstance(value, str):
        if wildcard_match(value, key):
            return False, True
        try:
            arr = json.loads(value)
        except ValueError:
            return True, False
        if not isinstance(arr, list) or not all(isinstance(x, str) for x in arr):
            return True, False
        return False, key in arr
    return True, False


ALL_IN = "all_in"        # every key present        (isIn / isAllIn)
ANY_IN = "any_in"        # at least one key present (isAnyIn)
ANY_NOT_IN = "any_not_in"  # at least one key absent  (isNotIn / isAnyNotIn)
ALL_NOT_IN = "all_not_in"  # no key present           (isAllNotIn)


def _set_exists_in_array(
    keys: list[str], value, mode: str, wildcard: bool
) -> tuple[bool, bool]:
    """in.go:110 setExistsInArray / anyin.go:69 anySetExistsInArray /
    allin.go allSetExistsInArray -> (invalid_type, result). ``wildcard``
    selects the Any/All-family per-element wildcard containment; In/NotIn
    use exact membership."""
    if isinstance(value, list):
        vals = []
        for v in value:
            if isinstance(v, str):
                vals.append(v)
            elif wildcard:  # Any/All families Sprint-coerce value elements
                vals.append(_sprint(v))
            else:
                return True, False
        return False, _contains(keys, vals, mode, wildcard)
    if isinstance(value, str):
        if len(keys) == 1 and keys[0] == value:
            return False, True  # short-circuits before the mode applies
        try:
            arr = json.loads(value)
        except ValueError:
            return True, False
        if not isinstance(arr, list) or not all(isinstance(x, str) for x in arr):
            return True, False
        return False, _contains(keys, arr, mode, wildcard)
    return True, False


def _contains(keys: list[str], vals: list[str], mode: str, use_wildcard: bool) -> bool:
    if use_wildcard:
        found = sum(1 for k in keys if any(wildcard_match(k, v) for v in vals))
    else:
        vset = set(vals)
        found = sum(1 for k in keys if k in vset)
    if mode == ALL_IN:
        return found == len(keys)
    if mode == ANY_IN:
        return found > 0
    if mode == ANY_NOT_IN:
        return found < len(keys)
    return found == 0  # ALL_NOT_IN


def _numeric_key_to_str(key):
    if isinstance(key, bool):
        return None
    if isinstance(key, (int, float)):
        return _sprint(key)
    return None


def _in(key, value) -> bool:
    k = key if isinstance(key, str) else _numeric_key_to_str(key)
    if k is not None:
        invalid, exists = _key_exists_in_array(k, value)
        return False if invalid else exists
    keys = _as_string_slice(key, coerce=False)
    if keys is not None:
        invalid, result = _set_exists_in_array(keys, value, ALL_IN, wildcard=False)
        return False if invalid else result
    return False


def _not_in(key, value) -> bool:
    k = key if isinstance(key, str) else _numeric_key_to_str(key)
    if k is not None:
        invalid, exists = _key_exists_in_array(k, value)
        return False if invalid else not exists
    keys = _as_string_slice(key, coerce=False)
    if keys is not None:
        invalid, result = _set_exists_in_array(keys, value, ANY_NOT_IN, wildcard=False)
        return False if invalid else result
    return False


def _any_in(key, value) -> bool:
    k = key if isinstance(key, str) else _numeric_key_to_str(key)
    if k is not None:
        invalid, exists = _key_exists_in_array(k, value)
        return False if invalid else exists
    keys = _as_string_slice(key, coerce=True)
    if keys is not None:
        invalid, result = _set_exists_in_array(keys, value, ANY_IN, wildcard=True)
        return False if invalid else result
    return False


def _all_in(key, value) -> bool:
    if isinstance(key, str):
        invalid, exists = _key_exists_in_array(key, value)
        return False if invalid else exists
    keys = _as_string_slice(key, coerce=True)
    if keys is not None:
        invalid, result = _set_exists_in_array(keys, value, ALL_IN, wildcard=True)
        return False if invalid else result
    return False


def _any_not_in(key, value) -> bool:
    k = key if isinstance(key, str) else _numeric_key_to_str(key)
    if k is not None:
        invalid, exists = _key_exists_in_array(k, value)
        return False if invalid else not exists
    keys = _as_string_slice(key, coerce=True)
    if keys is not None:
        invalid, result = _set_exists_in_array(keys, value, ANY_NOT_IN, wildcard=True)
        return False if invalid else result
    return False


def _all_not_in(key, value) -> bool:
    k = key if isinstance(key, str) else _numeric_key_to_str(key)
    if k is not None:
        invalid, exists = _key_exists_in_array(k, value)
        return False if invalid else not exists
    keys = _as_string_slice(key, coerce=True)
    if keys is not None:
        invalid, result = _set_exists_in_array(keys, value, ALL_NOT_IN, wildcard=True)
        return False if invalid else result
    return False


# ----------------------------------------------------------------- numeric


def _numeric(key, op: str, value) -> bool:
    """numeric.go NumericOperatorHandler."""
    if isinstance(key, bool):
        return False
    if isinstance(key, (int, float)):
        return _numeric_number_key(float(key), op, value)
    if isinstance(key, str):
        return _numeric_string_key(key, op, value)
    return False


def _numeric_number_key(key: float, op: str, value) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, (int, float)):
        return _compare(key, float(value), op)
    if isinstance(value, str):
        pair = _parse_duration_pair(key, value)
        if pair is not None:
            return _compare(pair[0], pair[1], op)
        try:
            return _compare(key, float(value), op)
        except ValueError:
            return False
    return False


def _numeric_string_key(key: str, op: str, value) -> bool:
    """numeric.go:144: duration pair, then float key, then int key, then
    resource quantity (whose value must be a quantity *string*)."""
    pair = _parse_duration_pair(key, value)
    if pair is not None:
        return _compare(pair[0], pair[1], op)
    try:
        kf = float(key)
    except ValueError:
        kf = None
    if kf is not None:
        return _numeric_number_key(kf, op, value)
    try:
        kq = parse_quantity(key)
    except QuantityError:
        return False
    if not isinstance(value, str):
        return False
    try:
        vq = parse_quantity(value)
    except QuantityError:
        return False
    cmp = -1 if kq < vq else (1 if kq > vq else 0)
    return _compare(float(cmp), 0.0, op)
