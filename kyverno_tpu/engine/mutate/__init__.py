"""Mutation handlers: strategic-merge patch, RFC6902 patches, overlay.

Mirrors /root/reference/pkg/engine/mutate/. The deprecated ``overlay`` form
is rewritten to patchStrategicMerge exactly as the reference does
(mutate/mutation.go:25-30).
"""

from .json_patch import apply_patch_ops, create_patch, generate_patches
from .strategic_merge import (
    ConditionError,
    GlobalConditionError,
    strategic_merge_patch,
)

__all__ = [
    "apply_patch_ops",
    "create_patch",
    "generate_patches",
    "ConditionError",
    "GlobalConditionError",
    "strategic_merge_patch",
]
