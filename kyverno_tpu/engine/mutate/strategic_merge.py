"""Strategic-merge patch: anchor preprocessing + schema-keyed list merge.

Mirrors /root/reference/pkg/engine/mutate/strategicPreprocessing.go (the
anchor-resolving walk run *before* the merge) and the kustomize kyaml
``patchstrategicmerge`` filter used at strategicMergePatch.go:100-107. The
reference leans on kyaml + the Kubernetes OpenAPI schema for merge keys;
here the merge is implemented directly on JSON trees with the well-known
k8s merge-key table, which covers the same policy corpus without dragging a
YAML object model onto the hot path.
"""

from __future__ import annotations

from ...utils.jsoncopy import json_copy

from ..anchors import (
    is_addition_anchor,
    is_condition_anchor,
    is_global_anchor,
    remove_anchor,
)
from ..validate_pattern import match_pattern


class ConditionError(Exception):
    """strategicPreprocessing.go:13: a condition anchor failed -> skip
    element (in lists) or the whole rule (in maps)."""


class GlobalConditionError(Exception):
    """strategicPreprocessing.go:25: a global anchor failed -> skip rule."""


def _contains_condition(key: str) -> bool:
    """anchor/common ContainsCondition: condition or global anchor."""
    return is_condition_anchor(key) or is_global_anchor(key)


def _has_anchor(key: str) -> bool:
    """strategicPreprocessing.go:262 hasAnchor."""
    return _contains_condition(key) or is_addition_anchor(key)


# ------------------------------------------------------------ preprocessing


def pre_process_pattern(pattern, resource):
    """strategicPreprocessing.go:47 preProcessPattern. Returns the
    anchor-resolved patch (a new tree); raises ConditionError /
    GlobalConditionError when the rule must be skipped."""
    pattern = json_copy(pattern)
    _pre_process_recursive(pattern, resource)
    if isinstance(pattern, dict):
        _delete_condition_elements(pattern)
    return pattern


def _pre_process_recursive(pattern, resource) -> None:
    if isinstance(pattern, dict):
        _walk_map(pattern, resource)
    elif isinstance(pattern, list):
        _walk_list(pattern, resource)


def _walk_map(pattern: dict, resource) -> None:
    """strategicPreprocessing.go:67 walkMap."""
    _validate_conditions(pattern, resource)
    _handle_addings(pattern, resource)

    for field in [k for k in pattern if not _has_anchor(k)]:
        resource_value = None
        if isinstance(resource, dict) and field in resource:
            resource_value = resource[field]
        _pre_process_recursive(pattern[field], resource_value)


def _walk_list(pattern: list, resource) -> None:
    """strategicPreprocessing.go:104 walkList."""
    if not pattern:
        return
    if isinstance(pattern[0], dict):
        _process_list_of_maps(pattern, resource)


def _process_list_of_maps(pattern: list, resource) -> None:
    """strategicPreprocessing.go:124 processListOfMaps: anchored pattern
    elements expand into per-resource-element patches keyed by "name"."""
    resource_elements = resource if isinstance(resource, list) else []
    new_elements = []

    for pattern_element in list(pattern):
        if not isinstance(pattern_element, dict):
            continue
        has_any_anchor = _has_anchors(pattern_element, _has_anchor)
        if not has_any_anchor:
            continue
        has_global = _has_anchors(pattern_element, is_global_anchor)

        any_global_passed = False
        last_global_error: GlobalConditionError | None = None

        for resource_element in resource_elements:
            candidate = json_copy(pattern_element)
            try:
                _pre_process_recursive(candidate, resource_element)
            except ConditionError:
                continue
            except GlobalConditionError as e:
                last_global_error = e
                continue

            if has_global:
                any_global_passed = True

            # kustomize matches list elements by name; elements without a
            # name can't be addressed, skip them (strategicPreprocessing.go:165)
            if not isinstance(resource_element, dict):
                continue
            name = resource_element.get("name")
            if not name:
                continue

            new_node = json_copy(candidate)
            if _delete_conditions_from_nested_maps(new_node):
                continue  # nothing left to patch
            new_node["name"] = name
            new_elements.append(new_node)

        if not any_global_passed and last_global_error is not None:
            raise last_global_error

    pattern.extend(new_elements)


def _has_anchors(pattern, is_anchor) -> bool:
    """strategicPreprocessing.go:264 hasAnchors (maps only, recursive)."""
    if isinstance(pattern, dict):
        for key, value in pattern.items():
            if is_anchor(key):
                return True
            if value is not None and _has_anchors(value, is_anchor):
                return True
    return False


def _validate_conditions(pattern: dict, resource) -> None:
    """strategicPreprocessing.go:211 validateConditions."""
    try:
        _validate_conditions_internal(pattern, resource, is_global_anchor)
    except ConditionError as e:
        raise GlobalConditionError(str(e)) from e
    _validate_conditions_internal(pattern, resource, is_condition_anchor)


def _validate_conditions_internal(pattern: dict, resource, key_filter) -> None:
    for key in [k for k in pattern if key_filter(k)]:
        bare, _ = remove_anchor(key)
        if not isinstance(resource, dict) or bare not in resource:
            raise ConditionError(f'could not find "{bare}" key in the resource')
        result = match_pattern(resource[bare], pattern[key])
        if not result.matched:
            raise ConditionError(result.message or f"condition failed for {bare}")


def _handle_addings(pattern: dict, resource) -> None:
    """strategicPreprocessing.go:231 handleAddings: +(key) is dropped when
    the resource already has the field, unwrapped otherwise."""
    for key in [k for k in pattern if is_addition_anchor(k)]:
        bare, _ = remove_anchor(key)
        value = pattern.pop(key)
        if isinstance(resource, dict) and bare in resource:
            continue  # resource already has this field
        pattern[bare] = value


def _delete_conditions_from_nested_maps(pattern) -> bool:
    """strategicPreprocessing.go:337: strip condition keys everywhere;
    returns True when the map became empty."""
    if not isinstance(pattern, dict):
        return False
    for key in list(pattern):
        if _contains_condition(key):
            del pattern[key]
        else:
            child = pattern[key]
            if child is not None and _delete_conditions_from_nested_maps(child):
                del pattern[key]
    return len(pattern) == 0


def _delete_condition_elements(pattern: dict) -> None:
    """strategicPreprocessing.go:380 deleteConditionElements."""
    for field in list(pattern):
        if _delete_anchors(pattern[field]):
            del pattern[field]


def _delete_anchors(node) -> bool:
    """strategicPreprocessing.go:398 deleteAnchors: remove anchors; return
    True when the node consisted only of anchors and must be dropped."""
    if isinstance(node, dict):
        return _delete_anchors_in_map(node)
    if isinstance(node, list):
        return _delete_anchors_in_list(node)
    return False


def _delete_anchors_in_map(node: dict) -> bool:
    for key in [k for k in node if _contains_condition(k)]:
        del node[key]
    need_to_delete = True
    for field in list(node):
        if _delete_anchors(node[field]):
            del node[field]
        else:
            need_to_delete = False
    return need_to_delete


def _delete_anchors_in_list(node: list) -> bool:
    was_empty = len(node) == 0
    for element in list(node):
        if _has_anchors(element, _has_anchor):
            node.remove(element)
        elif _delete_anchors(element):
            node.remove(element)
    return len(node) == 0 and not was_empty


# ------------------------------------------------------------ merge

# Well-known Kubernetes strategic-merge keys (a static slice of the OpenAPI
# x-kubernetes-patch-merge-key metadata kyaml consults).
_MERGE_KEY_CANDIDATES = ("name", "containerPort", "mountPath", "devicePath", "ip", "topologyKey")


def _find_merge_key(elements: list) -> str | None:
    for key in _MERGE_KEY_CANDIDATES:
        if all(isinstance(e, dict) and key in e for e in elements):
            return key
    return None


def merge(patch, base):
    """kyaml merge2 semantics on JSON trees: maps merge recursively (null
    deletes), keyed lists merge by merge key, everything else replaces."""
    if isinstance(patch, dict) and isinstance(base, dict):
        out = dict(base)
        for key, value in patch.items():
            if value is None:
                out.pop(key, None)
            elif key in out:
                out[key] = merge(value, out[key])
            else:
                out[key] = json_copy(value)
        return out
    if isinstance(patch, list) and isinstance(base, list):
        if patch and base:
            key = _find_merge_key(patch)
            if key is not None and all(isinstance(e, dict) and key in e for e in base):
                out = [json_copy(e) for e in base]
                index = {e[key]: i for i, e in enumerate(out)}
                for el in patch:
                    if el[key] in index:
                        out[index[el[key]]] = merge(el, out[index[el[key]]])
                    else:
                        out.append(json_copy(el))
                return out
        return json_copy(patch)
    return json_copy(patch)


def strategic_merge_patch(base: dict, overlay):
    """strategicMergePatch.go:85: preprocess anchors then merge. Returns the
    patched resource; a condition failure returns ``base`` unchanged (the
    reference substitutes an empty patch)."""
    try:
        patch = pre_process_pattern(overlay, base)
    except (ConditionError, GlobalConditionError):
        return json_copy(base)
    return merge(patch, base)
