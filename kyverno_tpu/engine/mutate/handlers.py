"""Mutate handler dispatch (mirrors /root/reference/pkg/engine/mutate/mutation.go).

Order matters and matches CreateMutateHandler: patchStrategicMerge,
patchesJson6902, overlay (rewritten to strategic merge), raw patches,
foreach."""

from __future__ import annotations

from dataclasses import dataclass, field

import yaml

from ..response import RuleStatus
from .json_patch import (
    JsonPatchError,
    apply_patch_ops,
    generate_patches,
    get_by_pointer,
)
from .strategic_merge import strategic_merge_patch


@dataclass
class MutateResult:
    status: RuleStatus = RuleStatus.PASS
    message: str = ""
    patches: list = field(default_factory=list)
    patched_resource: dict | None = None


def apply_mutation(mutation, resource: dict, foreach_index: int = 0) -> MutateResult:
    """CreateMutateHandler + Handle."""
    if mutation.patch_strategic_merge is not None:
        return process_strategic_merge(mutation.patch_strategic_merge, resource)
    if mutation.patches_json6902:
        return process_patches_json6902(mutation.patches_json6902, resource)
    if mutation.overlay is not None:
        # deprecated overlay is a strategic merge patch (mutation.go:25-30)
        return process_strategic_merge(mutation.overlay, resource)
    if mutation.patches:
        return process_raw_patches(mutation.patches, resource)
    if mutation.foreach:
        fe = mutation.foreach[foreach_index]
        if fe.patch_strategic_merge is None:
            return MutateResult(
                status=RuleStatus.FAIL,
                message="foreach mutation entry has no patchStrategicMerge",
                patched_resource=resource,
            )
        return process_strategic_merge(fe.patch_strategic_merge, resource)
    return MutateResult(patched_resource=resource, patches=[])


def process_strategic_merge(overlay, resource: dict) -> MutateResult:
    """strategicMergePatch.go:19 ProcessStrategicMergePatch."""
    if overlay is None:
        return MutateResult(
            status=RuleStatus.FAIL,
            message="empty patchStrategicMerge",
            patched_resource=resource,
        )
    try:
        patched = strategic_merge_patch(resource, overlay)
    except Exception as e:
        return MutateResult(
            status=RuleStatus.FAIL,
            message=f"failed to apply patchStrategicMerge: {e}",
            patched_resource=resource,
        )
    patches = generate_patches(resource, patched)
    return MutateResult(
        status=RuleStatus.PASS,
        message="successfully processed strategic merge patch",
        patches=patches,
        patched_resource=patched,
    )


def process_patches_json6902(patches_str: str, resource: dict) -> MutateResult:
    """patchJson6902.go:16 ProcessPatchJSON6902 (+ convertPatchesToJSON:
    the patch arrives as a YAML or JSON string)."""
    try:
        ops = yaml.safe_load(patches_str)
    except yaml.YAMLError as e:
        return MutateResult(
            status=RuleStatus.FAIL,
            message=f"failed to convert patchesJson6902 to JSON: {e}",
            patched_resource=resource,
        )
    if not isinstance(ops, list):
        return MutateResult(
            status=RuleStatus.FAIL,
            message="patchesJson6902 must be a list of RFC6902 operations",
            patched_resource=resource,
        )
    try:
        patched = apply_patch_ops(resource, ops)
    except JsonPatchError as e:
        return MutateResult(
            status=RuleStatus.FAIL,
            message=f"unable to apply RFC 6902 patches: {e}",
            patched_resource=resource,
        )
    patches = generate_patches(resource, patched)
    return MutateResult(
        status=RuleStatus.PASS,
        message="successfully process JSON6902 patches",
        patches=patches,
        patched_resource=patched,
    )


def process_raw_patches(raw_patches: list[dict], resource: dict) -> MutateResult:
    """patches.go:23 ProcessPatches: apply one-by-one; a failing 'remove'
    is skipped, any other failure fails the rule."""
    patched = resource
    applied: list[dict] = []
    errors: list[str] = []
    for patch in raw_patches:
        try:
            if patch.get("op") == "remove":
                # apply_patch_ops tolerates missing removes; the reference
                # (patches.go:55) skips them without recording the patch
                get_by_pointer(patched, patch.get("path", ""))
            patched = apply_patch_ops(patched, [patch])
        except JsonPatchError as e:
            if patch.get("op") == "remove":
                continue
            errors.append(str(e))
            continue
        applied.append(patch)
    if errors:
        return MutateResult(
            status=RuleStatus.FAIL,
            message=f"failed to process JSON patches: {';'.join(errors)}",
            patched_resource=resource,
        )
    return MutateResult(
        status=RuleStatus.PASS,
        message="successfully process JSON patches",
        patches=applied,
        patched_resource=patched,
    )
