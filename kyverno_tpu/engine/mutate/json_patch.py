"""RFC 6902 JSON Patch: apply, diff, and admission-response filtering.

The pip ``jsonpatch`` package is not available in the image, so this is a
from-scratch implementation of the pieces the engine needs:

- :func:`apply_patch_ops` mirrors evanphx/json-patch ApplyWithOptions with
  the reference's options (mutate/patchJson6902.go:76): negative indices,
  missing path on remove allowed, parent paths created on add.
- :func:`create_patch` mirrors mattbaird/jsonpatch CreatePatch (the
  before/after diff used at mutate/patchesUtils.go:12).
- :func:`generate_patches` adds the reference's filter + removal-reorder
  (mutate/patchesUtils.go:37 filterAndSortPatches).
"""

from __future__ import annotations

from ...utils.jsoncopy import json_copy
import re
from fnmatch import fnmatchcase


class JsonPatchError(Exception):
    pass


# ------------------------------------------------------------------ pointers


def _unescape(token: str) -> str:
    return token.replace("~1", "/").replace("~0", "~")


def escape_token(token: str) -> str:
    return token.replace("~", "~0").replace("/", "~1")


def _split_pointer(pointer: str) -> list[str]:
    if pointer == "":
        return []
    if not pointer.startswith("/"):
        raise JsonPatchError(f"invalid JSON pointer: {pointer!r}")
    return [_unescape(t) for t in pointer[1:].split("/")]


def _resolve_parent(doc, tokens: list[str], ensure: bool = False):
    """Walk to the parent container of the last token. With ``ensure``,
    missing intermediate objects are created (EnsurePathExistsOnAdd)."""
    node = doc
    for i, token in enumerate(tokens[:-1]):
        nxt = tokens[i + 1]
        if isinstance(node, dict):
            if token not in node:
                if not ensure:
                    raise JsonPatchError(f"path not found: /{'/'.join(tokens[:i + 1])}")
                node[token] = [] if nxt == "-" or _INT_RE.match(nxt) else {}
            node = node[token]
        elif isinstance(node, list):
            idx = _array_index(token, len(node), for_add=ensure)
            if idx == len(node):
                # EnsurePathExistsOnAdd appends a fresh container so the
                # remaining tokens have somewhere to land
                node.append([] if nxt == "-" or _INT_RE.match(nxt) else {})
            node = node[idx]
        else:
            raise JsonPatchError(f"cannot traverse scalar at /{'/'.join(tokens[:i + 1])}")
    return node


_INT_RE = re.compile(r"^-?\d+$")


def _array_index(token: str, length: int, for_add: bool) -> int:
    if token == "-":
        if not for_add:
            raise JsonPatchError("'-' only valid for add")
        return length
    if not _INT_RE.match(token):
        raise JsonPatchError(f"invalid array index {token!r}")
    idx = int(token)
    if idx < 0:  # SupportNegativeIndices
        idx += length
    limit = length + 1 if for_add else length
    if not 0 <= idx < limit:
        raise JsonPatchError(f"array index {token} out of bounds (len {length})")
    return idx


def get_by_pointer(doc, pointer: str):
    tokens = _split_pointer(pointer)
    node = doc
    for i, token in enumerate(tokens):
        if isinstance(node, dict):
            if token not in node:
                raise JsonPatchError(f"path not found: {pointer}")
            node = node[token]
        elif isinstance(node, list):
            node = node[_array_index(token, len(node), for_add=False)]
        else:
            raise JsonPatchError(f"cannot traverse scalar at {pointer}")
    return node


# ------------------------------------------------------------------ apply


def apply_patch_ops(doc, ops: list[dict]):
    """Apply an RFC6902 op list to a deep copy of ``doc``; returns the new
    document. Options match the reference (patchJson6902.go:76). Malformed
    ops surface as JsonPatchError (a failed rule), never as a crash."""
    result = json_copy(doc)
    for op in ops:
        try:
            result = _apply_one(result, op)
        except JsonPatchError:
            raise
        except (AttributeError, IndexError, KeyError, TypeError) as e:
            raise JsonPatchError(f"malformed patch op {op!r}: {e}") from e
    return result


def _apply_one(doc, op: dict):
    operation = op.get("op") or op.get("operation")
    path = op.get("path")
    if operation is None or path is None:
        raise JsonPatchError(f"invalid patch op: {op}")
    tokens = _split_pointer(path)

    if operation == "test":
        if get_by_pointer(doc, path) != op.get("value"):
            raise JsonPatchError(f"test failed at {path}")
        return doc
    if operation == "add":
        if not tokens:
            return json_copy(op.get("value"))
        parent = _resolve_parent(doc, tokens, ensure=True)
        _add(parent, tokens[-1], json_copy(op.get("value")))
        return doc
    if operation == "replace":
        if not tokens:
            return json_copy(op.get("value"))
        parent = _resolve_parent(doc, tokens)
        _replace(parent, tokens[-1], json_copy(op.get("value")))
        return doc
    if operation == "remove":
        try:
            parent = _resolve_parent(doc, tokens)
            _remove(parent, tokens[-1])
        except JsonPatchError:
            pass  # AllowMissingPathOnRemove
        return doc
    if operation == "move":
        value = get_by_pointer(doc, op["from"])
        from_tokens = _split_pointer(op["from"])
        _remove(_resolve_parent(doc, from_tokens), from_tokens[-1])
        parent = _resolve_parent(doc, tokens, ensure=True)
        _add(parent, tokens[-1], value)
        return doc
    if operation == "copy":
        value = json_copy(get_by_pointer(doc, op["from"]))
        parent = _resolve_parent(doc, tokens, ensure=True)
        _add(parent, tokens[-1], value)
        return doc
    raise JsonPatchError(f"unknown op {operation!r}")


def _add(parent, token: str, value) -> None:
    if isinstance(parent, dict):
        parent[token] = value
    elif isinstance(parent, list):
        parent.insert(_array_index(token, len(parent), for_add=True), value)
    else:
        raise JsonPatchError("add target is a scalar")


def _replace(parent, token: str, value) -> None:
    if isinstance(parent, dict):
        if token not in parent:
            raise JsonPatchError(f"replace path missing key {token!r}")
        parent[token] = value
    elif isinstance(parent, list):
        parent[_array_index(token, len(parent), for_add=False)] = value
    else:
        raise JsonPatchError("replace target is a scalar")


def _remove(parent, token: str) -> None:
    if isinstance(parent, dict):
        if token not in parent:
            raise JsonPatchError(f"remove path missing key {token!r}")
        del parent[token]
    elif isinstance(parent, list):
        del parent[_array_index(token, len(parent), for_add=False)]
    else:
        raise JsonPatchError("remove target is a scalar")


# ------------------------------------------------------------------ diff


def create_patch(src, dst) -> list[dict]:
    """mattbaird/jsonpatch CreatePatch: ops transforming src into dst."""
    ops: list[dict] = []
    _diff(src, dst, "", ops)
    return ops


def _strict_eq(a, b) -> bool:
    """Deep equality that — unlike Python's == — distinguishes bool from
    int/float (JSON true != 1) at any depth."""
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool) and a == b
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(_strict_eq(v, b[k]) for k, v in a.items())
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(_strict_eq(x, y) for x, y in zip(a, b))
    return type(a) is type(b) and a == b


def _diff(src, dst, path: str, ops: list[dict]) -> None:
    if _strict_eq(src, dst):
        return
    if isinstance(src, dict) and isinstance(dst, dict):
        for key in src:
            p = f"{path}/{escape_token(key)}"
            if key not in dst:
                ops.append({"op": "remove", "path": p})
            else:
                _diff(src[key], dst[key], p, ops)
        for key in dst:
            if key not in src:
                ops.append(
                    {"op": "add", "path": f"{path}/{escape_token(key)}", "value": dst[key]}
                )
        return
    if isinstance(src, list) and isinstance(dst, list):
        common = min(len(src), len(dst))
        for i in range(common):
            _diff(src[i], dst[i], f"{path}/{i}", ops)
        for i in range(common, len(dst)):  # additions
            ops.append({"op": "add", "path": f"{path}/{i}", "value": dst[i]})
        for i in range(len(src) - 1, common - 1, -1):  # removals, tail first
            ops.append({"op": "remove", "path": f"{path}/{i}"})
        return
    ops.append({"op": "replace", "path": path or "", "value": dst})


# ------------------------------------------------------------------ filters


def generate_patches(src, dst) -> list[dict]:
    """patchesUtils.go:12 generatePatches: diff then filter + sort."""
    return filter_and_sort_patches(create_patch(src, dst))


def filter_and_sort_patches(patches: list[dict]) -> list[dict]:
    """patchesUtils.go:37: drop ignored paths, then order runs of
    same-array index removals descending so they replay correctly.

    (The reference blindly reverses because its diff library emits
    ascending removals; create_patch above already emits descending, so
    only ascending runs are reversed here.)"""
    patches = [p for p in patches if not _ignore_patch(p["path"])]
    intervals = _get_remove_intervals(patches)
    if not intervals:
        return patches
    result = list(patches)
    for start, end in intervals:
        run = result[start : end + 1]
        indices = [int(p["path"].rsplit("/", 1)[1]) for p in run]
        if indices != sorted(indices, reverse=True):
            result[start : end + 1] = sorted(
                run, key=lambda p: int(p["path"].rsplit("/", 1)[1]), reverse=True
            )
    return result


_INDEX_SUFFIX = re.compile(r"/\d+$")


def _get_remove_intervals(patches: list[dict]) -> list[tuple[int, int]]:
    remove_paths = [
        p["path"] if p["op"] == "remove" and _INDEX_SUFFIX.search(p["path"]) else ""
        for p in patches
    ]
    res = []
    i = 0
    while i < len(remove_paths):
        if remove_paths[i]:
            base = remove_paths[i].rsplit("/", 1)[0]
            j = i + 1
            while j < len(remove_paths) and remove_paths[j] and (
                remove_paths[j].rsplit("/", 1)[0] == base
            ):
                j += 1
            if j - 1 != i:
                res.append((i, j - 1))
            i = j
        else:
            i += 1
    return res


def _ignore_patch(path: str) -> bool:
    """patchesUtils.go:129 ignorePatch: /status and non-allowlisted
    /metadata subtrees are dropped from the admission response."""
    if "/status" in path:
        return True
    if fnmatchcase(path, "*/metadata"):
        return False
    if "/metadata" in path:
        if (
            "/metadata/name" not in path
            and "/metadata/namespace" not in path
            and "/metadata/annotations" not in path
            and "/metadata/labels" not in path
        ):
            return True
    return False
