"""Batched mutate tier: device-gated screening + single-pass merge/patch.

SURVEY section 7 step 7: "batch the anchor-condition gate on TPU so only
matching resources hit the CPU mutator". Each mutate rule's gate
(match/exclude/preconditions) compiles into the same device tensors a
validate rule's gate does — with an empty pattern, so a gate that passes
scores PASS and a non-matching resource scores NOT_APPLICABLE/SKIP. One
device evaluation screens the whole batch; documents no rule touches never
reach the CPU mutator.

For documents that do, a compiled fast path applies the strategic merge and
emits the RFC6902 ops in one walk (``merge_emit``), skipping the per-doc
context build, variable-substitution scan, and full-tree diff of the serial
engine — while producing byte-identical patches (parity suites in
tests/unit/test_batch_mutate.py). Rules the fast path cannot prove static
(variables, foreach, external context) fall back to the full engine per
document, so coverage is total.

Reference semantics: /root/reference/pkg/engine/mutation.go:31 (Mutate,
rule chaining), mutate/strategicMergePatch.go:85 (preprocess + merge),
mutate/patchesUtils.go:12 (generatePatches).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

import numpy as np

from ...api.types import ClusterPolicy, Rule, Spec, Validation
from ...utils.jsoncopy import json_copy
from ..context import Context
from ..match import matches_resource_description
from ..policy_context import PolicyContext
from .handlers import apply_mutation
from .json_patch import _diff, escape_token, filter_and_sort_patches
from .strategic_merge import (
    ConditionError,
    GlobalConditionError,
    _find_merge_key,
    _has_anchor,
    _has_anchors,
    merge,
    pre_process_pattern,
)

# ------------------------------------------------------- fast merge + ops


def merge_emit(patch, base, path: str, ops: list) -> object:
    """``merge(patch, base)`` plus the RFC6902 ops that
    ``_diff(base, merge(patch, base))`` would emit — in one walk that never
    visits siblings the patch does not touch. Op order matches _diff
    exactly: base-key iteration order for removals/changes, then patch-key
    order for additions; keyed-list merges compare touched indices
    ascending and append new elements at the tail."""
    if isinstance(patch, dict) and isinstance(base, dict):
        out = dict(base)
        for key in base:
            if key not in patch:
                continue
            p = f"{path}/{escape_token(key)}"
            if patch[key] is None:
                del out[key]
                ops.append({"op": "remove", "path": p})
            else:
                out[key] = merge_emit(patch[key], base[key], p, ops)
        for key, value in patch.items():
            if key in base or value is None:
                continue
            value = json_copy(value)
            out[key] = value
            ops.append({"op": "add", "path": f"{path}/{escape_token(key)}",
                        "value": value})
        return out
    if isinstance(patch, list) and isinstance(base, list):
        if patch and base:
            key = _find_merge_key(patch)
            if key is not None and all(isinstance(e, dict) and key in e
                                       for e in base):
                out = list(base)
                index = {e[key]: i for i, e in enumerate(out)}
                touched = set()
                appended = []
                for el in patch:
                    i = index.get(el[key])
                    if i is not None:
                        touched.add(i)
                        out[i] = merge(el, out[i])
                    else:
                        appended.append(json_copy(el))
                for i in sorted(touched):
                    _diff(base[i], out[i], f"{path}/{i}", ops)
                for j, el in enumerate(appended):
                    out.append(el)
                    ops.append({"op": "add",
                                "path": f"{path}/{len(base) + j}",
                                "value": el})
                return out
        out = json_copy(patch)
        _diff(base, out, path, ops)
        return out
    out = json_copy(patch)
    _diff(base, out, path, ops)
    return out


def fast_strategic_merge(resource: dict, overlay, has_anchors: bool):
    """strategic_merge_patch + generate_patches in a single pass.
    Returns (patched_resource, ops); a condition failure returns the
    resource unchanged with no ops (the reference substitutes an empty
    patch, strategicMergePatch.go:29)."""
    if has_anchors:
        try:
            patch = pre_process_pattern(overlay, resource)
        except (ConditionError, GlobalConditionError):
            return resource, []
    else:
        patch = overlay
    ops: list = []
    patched = merge_emit(patch, resource, "", ops)
    return patched, filter_and_sort_patches(ops)


# ------------------------------------------------------------- batch tier


def _is_static_mutation(rule: Rule) -> bool:
    """A rule the fast path may apply: no external context, no foreach, and
    no variable/reference syntax anywhere in the mutation block (escaped
    forms included — the engine's substitution pass would rewrite them)."""
    if rule.context or rule.mutation.foreach:
        return False
    blob = json.dumps([
        rule.mutation.patch_strategic_merge,
        rule.mutation.overlay,
        rule.mutation.patches,
        rule.mutation.patches_json6902,
    ], default=str)
    return "{{" not in blob and "$(" not in blob


@dataclass
class _FastRule:
    rule: Rule
    overlay: object          # strategic-merge pattern or None (6902/raw)
    has_anchors: bool
    gate_index: int          # column in the gate verdict matrix


@dataclass
class DocMutation:
    patches: list = field(default_factory=list)
    patched_resource: dict | None = None


class BatchMutator:
    """Compile a policy set's mutate tier once; apply it to many documents.

    The serial-engine equivalent of ``apply([doc])`` is the webhook's
    per-policy chain (mutation.go:110: rule N's patched resource feeds rule
    N+1); parity is asserted patch-for-patch in the test suite."""

    def __init__(self, policies: list, min_gate_batch: int = 64):
        self.policies = [p for p in policies
                         if any(r.has_mutate() for r in p.spec.rules)]
        self.min_gate_batch = min_gate_batch
        self.plan: list[tuple] = []      # (policy, "fast"|"engine", rules)
        gate_policies: list[ClusterPolicy] = []
        n_gates = 0
        for policy in self.policies:
            fast_rules: list[_FastRule] = []
            ok = True
            for rule in policy.spec.rules:
                if not rule.has_mutate():
                    continue
                if not _is_static_mutation(rule):
                    ok = False
                    break
                overlay = (rule.mutation.patch_strategic_merge
                           if rule.mutation.patch_strategic_merge is not None
                           else rule.mutation.overlay)
                fast_rules.append(_FastRule(
                    rule=rule, overlay=overlay,
                    has_anchors=_has_anchors(overlay, _has_anchor),
                    gate_index=-1))
            if ok and fast_rules:
                # gate columns are assigned only for policies that stay
                # fast — a discarded policy must not shift later columns
                for fr in fast_rules:
                    fr.gate_index = n_gates
                    n_gates += 1
                self.plan.append((policy, "fast", fast_rules))
                # synthetic gate policy: the mutate rule's match/exclude/
                # preconditions with an empty validate pattern — PASS means
                # "this rule applies to this resource"
                gate_policies.append(ClusterPolicy(
                    api_version=policy.api_version, kind=policy.kind,
                    metadata=dict(policy.metadata),
                    spec=Spec(rules=[
                        Rule(name=fr.rule.name, match=fr.rule.match,
                             exclude=fr.rule.exclude,
                             preconditions=fr.rule.preconditions,
                             validation=Validation(pattern={}))
                        for fr in fast_rules])))
            else:
                self.plan.append((policy, "engine", None))
        self._gate_cps = None
        self._gate_trivial = True
        self._gate_choice: bool | None = None   # measured lane decision
        if gate_policies:
            from ...models import CompiledPolicySet

            self._gate_cps = CompiledPolicySet(gate_policies)
            t = self._gate_cps.tensors
            # a gate is "trivial" when it only checks resource kinds — the
            # host comparison is then cheaper than shipping the batch to
            # the device; selectors, name globs, preconditions or exclude
            # predicates make the device screen pay for itself
            self._gate_trivial = (
                len(t.chk_path) == 0
                and bool((np.asarray(t.ax_path) < 0).all())
                and bool((np.asarray(t.ax_nfa) < 0).all()))

    # ------------------------------------------------------------- gates

    def _host_gate(self, policy, rule: Rule, resource: dict) -> bool:
        ok, _ = matches_resource_description(
            resource, rule, policy_namespace=policy.namespace)
        if not ok:
            return False
        if rule.preconditions is None:
            return True
        from ..validation import check_preconditions

        jctx = Context()
        jctx.add_resource(resource)
        pctx = PolicyContext(policy=policy, new_resource=resource,
                             json_context=jctx)
        try:
            return check_preconditions(pctx, rule.preconditions)
        except Exception:
            return False

    def gate_verdicts(self, resources: list[dict],
                      chunk: int = 8192) -> np.ndarray | None:
        """Device-screen the gate matrix (HOST cells oracle-resolved),
        chunked so a large scan never ships one giant transfer. Chunks pad
        to power-of-two shape buckets so XLA compiles once per bucket, not
        once per chunk."""
        from ...models.flatten import pad_to_buckets_packed

        if self._gate_cps is None:
            return None
        try:
            outs = []
            for i in range(0, len(resources), chunk):
                rs = resources[i:i + chunk]
                batch, n0 = pad_to_buckets_packed(
                    self._gate_cps.flatten_packed(rs))
                v = self._gate_cps.evaluate_device(batch)[:n0]
                outs.append(self._gate_cps.resolve_host_cells(rs, v))
            return outs[0] if len(outs) == 1 else np.vstack(outs)
        except Exception:
            return None

    def _auto_gate(self, resources: list[dict]) -> bool:
        """Measured routing, same philosophy as the admission router
        (runtime/batch.py): the device screen engages only when its
        measured per-doc cost beats the host gate's — behind a high-RTT
        link the host comparison wins, on a local chip the device does.
        The choice is calibrated once on a sample and cached."""
        import time

        if (self._gate_cps is None or self._gate_trivial
                or len(resources) < self.min_gate_batch):
            return False
        if self._gate_choice is not None:
            return self._gate_choice
        sample = resources[:128]
        self.gate_verdicts(sample[:8])          # warm the shape buckets
        t0 = time.monotonic()
        dev_ok = self.gate_verdicts(sample) is not None
        dev_per_doc = (time.monotonic() - t0) / len(sample)
        fast_pairs = [(p, fr.rule) for p, mode, frs in self.plan
                      if mode == "fast" for fr in frs]
        t0 = time.monotonic()
        for doc in sample:
            for policy, rule in fast_pairs:
                self._host_gate(policy, rule, doc)
        host_per_doc = (time.monotonic() - t0) / len(sample)
        self._gate_choice = dev_ok and dev_per_doc < host_per_doc
        return self._gate_choice

    # ------------------------------------------------------------- apply

    def apply(self, resources: list[dict],
              use_device_gate: bool | None = None) -> list[DocMutation]:
        from ...models import Verdict

        gate = None
        if use_device_gate is None:
            use_device_gate = self._auto_gate(resources)
        if use_device_gate:
            gate = self.gate_verdicts(resources)

        out: list[DocMutation] = []
        for b, doc in enumerate(resources):
            resource = doc
            patches: list = []
            dirty = False   # a patch landed: later gates must re-check on
            #                 the patched doc (mutation.go:110 chaining)
            for policy, mode, fast_rules in self.plan:
                if mode == "engine":
                    from ..mutation import mutate as engine_mutate

                    jctx = Context()
                    jctx.add_resource(resource)
                    resp = engine_mutate(PolicyContext(
                        policy=policy, new_resource=resource,
                        json_context=jctx))
                    if resp.patches:
                        patches.extend(resp.patches)
                        dirty = True
                    if resp.patched_resource is not None:
                        resource = resp.patched_resource
                    continue
                for fr in fast_rules:
                    applies = None
                    if gate is not None and not dirty:
                        v = int(gate[b, fr.gate_index])
                        if v == Verdict.PASS:
                            applies = True
                        elif v in (Verdict.SKIP, Verdict.NOT_APPLICABLE):
                            applies = False
                        # ERROR/unexpected -> conservative host gate
                    if applies is None:
                        applies = self._host_gate(policy, fr.rule, resource)
                    if not applies:
                        continue
                    if fr.overlay is not None:
                        patched, ops = fast_strategic_merge(
                            resource, fr.overlay, fr.has_anchors)
                    else:
                        result = apply_mutation(fr.rule.mutation, resource)
                        patched, ops = result.patched_resource, result.patches
                    if ops:
                        patches.extend(ops)
                        resource = patched
                        dirty = True
            out.append(DocMutation(patches=patches, patched_resource=resource))
        return out
