"""Validation driver: iterate rules, match -> context -> preconditions ->
pattern / anyPattern / deny / foreach.

Mirrors /root/reference/pkg/engine/validation.go (Validate:26,
validateResource:78, validator.validate:175, validateForEach:204,
validatePatterns:421). Pure function of PolicyContext -> EngineResponse; the
TPU tier (kyverno_tpu.models / kyverno_tpu.ops) compiles the same semantics
into batched kernels and is cross-checked against this implementation.
"""

from __future__ import annotations

import time

from .json_context_loader import load_context
from .match import matches_resource_description
from .operators import evaluate_conditions
from .policy_context import PolicyContext
from .response import (
    EngineResponse,
    PolicyResponse,
    PolicySpecSummary,
    ResourceSpec,
    RuleResponse,
    RuleStatus,
    RuleType,
)
from .validate_pattern import match_pattern
from .variables import (
    VariableResolutionError,
    substitute_all,
    substitute_all_in_preconditions,
)


def validate(policy_ctx: PolicyContext) -> EngineResponse:
    """validation.go:26 Validate."""
    start = time.monotonic()
    resp = _validate_resource(policy_ctx)
    _build_response(policy_ctx, resp, start)
    return resp


def _build_response(ctx: PolicyContext, resp: EngineResponse, start: float) -> None:
    """validation.go:53 buildResponse."""
    if resp.patched_resource is None:
        # for DELETE the patched resource is the old resource
        resp.patched_resource = ctx.new_resource or ctx.old_resource

    resource = resp.patched_resource or {}
    meta = resource.get("metadata") or {}
    resp.policy_response.policy = PolicySpecSummary(
        name=ctx.policy.name,
        validation_failure_action=ctx.policy.spec.validation_failure_action,
    )
    resp.policy_response.resource = ResourceSpec(
        kind=resource.get("kind", ""),
        api_version=resource.get("apiVersion", ""),
        namespace=meta.get("namespace", ""),
        name=meta.get("name", ""),
        uid=meta.get("uid", ""),
    )
    resp.policy_response.processing_time_s = time.monotonic() - start


def _validate_resource(ctx: PolicyContext) -> EngineResponse:
    """validation.go:78 validateResource."""
    resp = EngineResponse(policy_response=PolicyResponse())

    ctx.json_context.checkpoint()
    try:
        for rule in ctx.policy.spec.rules:
            if not rule.has_validate():
                continue
            if not _matches(rule, ctx):
                continue
            ctx.json_context.reset()
            start = time.monotonic()
            rule_resp = _process_validation_rule(ctx, rule)
            if rule_resp is not None:
                _add_rule_response(resp, rule_resp, start)
    finally:
        ctx.json_context.restore()

    return resp


def _matches(rule, ctx: PolicyContext) -> bool:
    """validation.go:383 matches: new OR old resource satisfies match/exclude.

    The reference passes "" for policyNamespace here (validation.go:384)
    because its webhook always pre-filters namespaced policies through the
    policy cache (policycache/cache.go:89). This engine is also entered with
    unfiltered policy sets (CompiledPolicySet, CLI), so the namespace gate of
    utils.go:272 is applied here, as the reference's mutation path does
    (mutation.go:63)."""
    ns = ctx.policy.namespace if ctx.policy is not None else ""
    ok, _ = matches_resource_description(
        ctx.new_resource,
        rule,
        ctx.admission_info,
        ctx.exclude_group_role,
        ctx.namespace_labels,
        ns,
    )
    if ok:
        return True
    if ctx.old_resource:
        ok, _ = matches_resource_description(
            ctx.old_resource,
            rule,
            ctx.admission_info,
            ctx.exclude_group_role,
            ctx.namespace_labels,
            ns,
        )
        if ok:
            return True
    return False


def _process_validation_rule(ctx: PolicyContext, rule) -> RuleResponse | None:
    if rule.validation.foreach:
        return _Validator.for_rule(ctx, rule).validate_foreach()
    return _Validator.for_rule(ctx, rule).validate()


def _add_rule_response(resp: EngineResponse, rule_resp: RuleResponse, start: float) -> None:
    """validation.go:118 addRuleResponse."""
    rule_resp.processing_time_s = time.monotonic() - start
    if rule_resp.status in (RuleStatus.PASS, RuleStatus.FAIL):
        resp.policy_response.rules_applied_count += 1
    elif rule_resp.status is RuleStatus.ERROR:
        resp.policy_response.rules_error_count += 1
    resp.policy_response.rules.append(rule_resp)


def check_preconditions(ctx: PolicyContext, any_all_conditions) -> bool:
    """utils.go:445 checkPreconditions. Raises on substitution failure."""
    if any_all_conditions is None:
        return True
    substituted = substitute_all_in_preconditions(ctx.json_context, any_all_conditions)
    conditions = transform_conditions(substituted)
    return evaluate_conditions(conditions)


def transform_conditions(original):
    """utils.go:392 transformConditions: accept {any/all} dict or bare list."""
    if isinstance(original, dict):
        if set(original) <= {"any", "all"}:
            return original
        raise ValueError("invalid preconditions")
    if isinstance(original, list):
        return original
    raise ValueError("invalid preconditions")


def evaluate_list(jmespath_expr: str, json_ctx):
    """utils.go:460 evaluateList: non-list results wrap into a single-element
    list."""
    result = json_ctx.query(jmespath_expr)
    if isinstance(result, list):
        return result
    return [result]


def rule_response(rule, rule_type: RuleType, msg: str, status: RuleStatus) -> RuleResponse:
    return RuleResponse(name=rule.name, type=rule_type, message=msg, status=status)


def rule_error(rule, rule_type: RuleType, msg: str, err: Exception) -> RuleResponse:
    return RuleResponse(
        name=rule.name,
        type=rule_type,
        message=f"{msg}: {err}",
        status=RuleStatus.ERROR,
    )


class _Validator:
    """validation.go:132 validator struct."""

    def __init__(self, ctx, rule, context_entries, conditions, pattern, any_pattern, deny):
        self.ctx = ctx
        self.rule = rule
        self.context_entries = context_entries
        self.any_all_conditions = conditions
        self.pattern = pattern
        self.any_pattern = any_pattern
        self.deny = deny

    @classmethod
    def for_rule(cls, ctx: PolicyContext, rule) -> "_Validator":
        return cls(
            ctx,
            rule,
            rule.context,
            rule.preconditions,
            rule.validation.pattern,
            rule.validation.any_pattern,
            rule.validation.deny,
        )

    @classmethod
    def for_foreach(cls, ctx: PolicyContext, rule, foreach) -> "_Validator":
        """validation.go:156 newForeachValidator."""
        return cls(
            ctx,
            rule,
            foreach.context,
            foreach.preconditions,
            foreach.pattern,
            foreach.any_pattern,
            foreach.deny,
        )

    # ------------------------------------------------------------ driver

    def validate(self) -> RuleResponse | None:
        """validation.go:175 validator.validate."""
        try:
            load_context(self.context_entries, self.ctx, self.rule.name)
        except Exception as e:
            return rule_error(self.rule, RuleType.VALIDATION, "failed to load context", e)

        try:
            preconditions_passed = check_preconditions(self.ctx, self.any_all_conditions)
        except Exception as e:
            return rule_error(
                self.rule, RuleType.VALIDATION, "failed to evaluate preconditions", e
            )
        if not preconditions_passed:
            return rule_response(
                self.rule, RuleType.VALIDATION, "preconditions not met", RuleStatus.SKIP
            )

        if self.pattern is not None or self.any_pattern is not None:
            try:
                self._substitute_patterns()
            except VariableResolutionError as e:
                return rule_error(
                    self.rule, RuleType.VALIDATION, "variable substitution failed", e
                )
            return self._validate_resource_with_rule()

        if self.deny is not None:
            return self._validate_deny()

        return None  # invalid rule: neither patterns nor deny

    def validate_foreach(self) -> RuleResponse | None:
        """validation.go:204 validateForEach."""
        try:
            load_context(self.context_entries, self.ctx, self.rule.name)
        except Exception as e:
            return rule_error(self.rule, RuleType.VALIDATION, "failed to load context", e)

        try:
            preconditions_passed = check_preconditions(self.ctx, self.any_all_conditions)
        except Exception as e:
            return rule_error(
                self.rule, RuleType.VALIDATION, "failed to evaluate preconditions", e
            )
        if not preconditions_passed:
            return rule_response(
                self.rule, RuleType.VALIDATION, "preconditions not met", RuleStatus.SKIP
            )

        apply_count = 0
        for foreach in self.rule.validation.foreach:
            try:
                elements = evaluate_list(foreach.list_expr, self.ctx.json_context)
            except Exception:
                continue

            self.ctx.json_context.checkpoint()
            try:
                for element in elements:
                    self.ctx.json_context.reset()
                    ctx = self.ctx.copy()
                    try:
                        _add_element_to_context(ctx, element)
                    except Exception as e:
                        return rule_error(
                            self.rule, RuleType.VALIDATION, "failed to process foreach", e
                        )
                    r = _Validator.for_foreach(ctx, self.rule, foreach).validate()
                    if r is None or r.status is RuleStatus.SKIP:
                        continue
                    if r.status is not RuleStatus.PASS:
                        return rule_response(
                            self.rule,
                            RuleType.VALIDATION,
                            f"validation failed in foreach rule for {r.message}",
                            r.status,
                        )
                    apply_count += 1
            finally:
                self.ctx.json_context.restore()

        if apply_count == 0:
            return rule_response(
                self.rule, RuleType.VALIDATION, "rule skipped", RuleStatus.SKIP
            )
        return rule_response(self.rule, RuleType.VALIDATION, "rule passed", RuleStatus.PASS)

    # ------------------------------------------------------------ checks

    def _validate_resource_with_rule(self) -> RuleResponse | None:
        """validation.go:341 validateResourceWithRule: CREATE/DELETE/MODIFY
        dispatch; foreach elements validate directly."""
        if self.ctx.element is not None:
            return self._validate_patterns(self.ctx.element)
        if not self.ctx.old_resource:
            return self._validate_patterns(self.ctx.new_resource)
        if not self.ctx.new_resource:
            return None  # DELETE: skip validation on deleted resource
        old_resp = self._validate_patterns(self.ctx.old_resource)
        new_resp = self._validate_patterns(self.ctx.new_resource)
        if _is_same_rule_response(old_resp, new_resp):
            return None  # MODIFY with unchanged verdict
        return new_resp

    def _validate_patterns(self, resource: dict) -> RuleResponse:
        """validation.go:421 validatePatterns."""
        if self.pattern is not None:
            result = match_pattern(resource, self.pattern)
            if not result.matched:
                if result.skip:
                    return rule_response(
                        self.rule, RuleType.VALIDATION, result.message, RuleStatus.SKIP
                    )
                if result.path == "":
                    return rule_response(
                        self.rule,
                        RuleType.VALIDATION,
                        self._build_error_message(result.message, ""),
                        RuleStatus.ERROR,
                    )
                return rule_response(
                    self.rule,
                    RuleType.VALIDATION,
                    self._build_error_message(result.message, result.path),
                    RuleStatus.FAIL,
                )
            return rule_response(
                self.rule,
                RuleType.VALIDATION,
                f"validation rule '{self.rule.name}' passed.",
                RuleStatus.PASS,
            )

        if self.any_pattern is not None:
            if not isinstance(self.any_pattern, list):
                return rule_response(
                    self.rule,
                    RuleType.VALIDATION,
                    "failed to deserialize anyPattern, expected type array",
                    RuleStatus.ERROR,
                )
            failures: list[str] = []
            for idx, pattern in enumerate(self.any_pattern):
                result = match_pattern(resource, pattern)
                if result.matched:
                    return rule_response(
                        self.rule,
                        RuleType.VALIDATION,
                        f"validation rule '{self.rule.name}' anyPattern[{idx}] passed.",
                        RuleStatus.PASS,
                    )
                if result.path == "":
                    failures.append(
                        f"Rule {self.rule.name}[{idx}] failed: {result.message}."
                    )
                else:
                    failures.append(
                        f"Rule {self.rule.name}[{idx}] failed at path {result.path}."
                    )
            if failures:
                return rule_response(
                    self.rule,
                    RuleType.VALIDATION,
                    _build_any_pattern_error_message(self.rule, failures),
                    RuleStatus.FAIL,
                )

        return rule_response(
            self.rule,
            RuleType.VALIDATION,
            self.rule.validation.message,
            RuleStatus.PASS,
        )

    def _validate_deny(self) -> RuleResponse:
        """validation.go:299 validateDeny."""
        try:
            deny = substitute_all(self.ctx.json_context, self.deny)
        except VariableResolutionError as e:
            return rule_error(
                self.rule,
                RuleType.VALIDATION,
                "failed to substitute variables in deny conditions",
                e,
            )
        try:
            conditions = transform_conditions(deny.get("conditions"))
        except ValueError as e:
            return rule_error(self.rule, RuleType.VALIDATION, "invalid deny conditions", e)

        denied = evaluate_conditions(conditions)
        if denied:
            return rule_response(
                self.rule,
                RuleType.VALIDATION,
                self._deny_message(denied),
                RuleStatus.FAIL,
            )
        return rule_response(
            self.rule, RuleType.VALIDATION, self._deny_message(denied), RuleStatus.PASS
        )

    # ------------------------------------------------------------ helpers

    def _deny_message(self, denied: bool) -> str:
        """validation.go:323 getDenyMessage."""
        if not denied:
            return f"validation rule '{self.rule.name}' passed."
        msg = self.rule.validation.message
        if not msg:
            return f"validation error: rule {self.rule.name} failed"
        try:
            return substitute_all(self.ctx.json_context, msg)
        except VariableResolutionError:
            return msg

    def _build_error_message(self, err_msg: str, path: str) -> str:
        """validation.go:507 buildErrorMessage."""
        if not self.rule.validation.message:
            if path:
                return f"validation error: rule {self.rule.name} failed at path {path}"
            return (
                f"validation error: rule {self.rule.name} execution error: {err_msg}"
            )
        try:
            msg = substitute_all(self.ctx.json_context, self.rule.validation.message)
        except VariableResolutionError:
            msg = self.rule.validation.message
        if not msg.endswith("."):
            msg += "."
        if path:
            return f"validation error: {msg} Rule {self.rule.name} failed at path {path}"
        return f"validation error: {msg} Rule {self.rule.name} execution error: {err_msg}"

    def _substitute_patterns(self) -> None:
        """validation.go:545 substitutePatterns."""
        if self.pattern is not None:
            self.pattern = substitute_all(self.ctx.json_context, self.pattern)
        elif self.any_pattern is not None:
            self.any_pattern = substitute_all(self.ctx.json_context, self.any_pattern)


def _build_any_pattern_error_message(rule, errors: list[str]) -> str:
    """validation.go:531 buildAnyPatternErrorMessage."""
    err_str = " ".join(errors)
    msg = rule.validation.message
    if not msg:
        return f"validation error: {err_str}"
    if msg.endswith("."):
        return f"validation error: {msg} {err_str}"
    return f"validation error: {msg}. {err_str}"


def _add_element_to_context(ctx: PolicyContext, element) -> None:
    """validation.go:268 addElementToContext."""
    if not isinstance(element, dict):
        raise ValueError(f"failed to convert foreach element to map: {element!r}")
    ctx.json_context.add_json({"element": element})
    ctx.element = element


def _is_same_rule_response(r1: RuleResponse | None, r2: RuleResponse | None) -> bool:
    """validation.go:401 isSameRuleResponse."""
    if r1 is None or r2 is None:
        return r1 is r2
    return (
        r1.name == r2.name
        and r1.type == r2.type
        and r1.message == r2.message
        and r1.status == r2.status
    )
