"""Generate engine: admission-time filtering + resource materialization.

Mirrors /root/reference/pkg/engine/generation.go (the filter run inline at
admission, producing GenerateRequest work items) and the materialization
half of the async generate controller
(/root/reference/pkg/generate/generate.go:482-560 manageData/manageClone),
exposed as library functions so the CLI and the controller share them.
"""

from __future__ import annotations

import copy
import time

from .json_context_loader import load_context
from .match import matches_resource_description
from .policy_context import PolicyContext
from .response import (
    EngineResponse,
    PolicyResponse,
    PolicySpecSummary,
    ResourceSpec,
    RuleResponse,
    RuleStatus,
    RuleType,
)
from .validation import check_preconditions, rule_response
from .variables import VariableResolutionError, substitute_all


def generate(policy_ctx: PolicyContext) -> EngineResponse:
    """generation.go:16 Generate: returns the generate rules applicable to
    this (policy, resource) — PASS rows become GenerateRequests."""
    start = time.monotonic()
    resp = EngineResponse(policy_response=PolicyResponse())
    resource = policy_ctx.new_resource or {}
    meta = resource.get("metadata") or {}
    resp.policy_response.policy = PolicySpecSummary(name=policy_ctx.policy.name)
    resp.policy_response.resource = ResourceSpec(
        kind=resource.get("kind", ""),
        api_version=resource.get("apiVersion", ""),
        namespace=meta.get("namespace", ""),
        name=meta.get("name", ""),
    )

    if policy_ctx.excluded_by_func(
        resource.get("kind", ""), meta.get("namespace", ""), meta.get("name", "")
    ):
        return resp

    for rule in policy_ctx.policy.spec.rules:
        rule_resp = _filter_rule(rule, policy_ctx)
        if rule_resp is not None:
            resp.policy_response.rules.append(rule_resp)

    resp.policy_response.processing_time_s = time.monotonic() - start
    return resp


def _filter_rule(rule, policy_ctx: PolicyContext) -> RuleResponse | None:
    """generation.go:58 filterRule."""
    if not rule.has_generate():
        return None

    # policy-namespace gate applied engine-side (see validation._matches)
    ns = policy_ctx.policy.namespace if policy_ctx.policy is not None else ""
    ok, _ = matches_resource_description(
        policy_ctx.new_resource,
        rule,
        policy_ctx.admission_info,
        policy_ctx.exclude_group_role,
        policy_ctx.namespace_labels,
        ns,
    )
    if not ok:
        # old resource matching means the GR must be cleaned up -> FAIL row
        old_ok, _ = matches_resource_description(
            policy_ctx.old_resource,
            rule,
            policy_ctx.admission_info,
            policy_ctx.exclude_group_role,
            policy_ctx.namespace_labels,
            ns,
        )
        if policy_ctx.old_resource and old_ok:
            return rule_response(rule, RuleType.GENERATION, "", RuleStatus.FAIL)
        return None

    policy_ctx.json_context.checkpoint()
    try:
        try:
            load_context(rule.context, policy_ctx, rule.name)
        except Exception:
            return None
        try:
            if not check_preconditions(policy_ctx, rule.preconditions):
                return None
        except Exception:
            return None
    finally:
        policy_ctx.json_context.restore()

    return rule_response(rule, RuleType.GENERATION, "", RuleStatus.PASS)


# ------------------------------------------------------------ materialization

MODE_SKIP = "SKIP"
MODE_CREATE = "CREATE"
MODE_UPDATE = "UPDATE"

GENERATED_BY_LABELS = {
    "policy": "kyverno.io/generated-by-policy",
    "rule": "kyverno.io/generated-by-rule",
    "kind": "kyverno.io/generated-by-kind",
    "namespace": "kyverno.io/generated-by-namespace",
    "name": "kyverno.io/generated-by-name",
}


class GenerateError(Exception):
    pass


def apply_generate_rule(rule, policy_ctx: PolicyContext, trigger: dict,
                        client=None) -> tuple[dict | None, str]:
    """generate.go:332 applyRule: substitute variables in the generate spec,
    materialize from data: or clone:, and label the result for tracking.

    Returns (resource-or-None, mode). ``client`` provides get_resource for
    clone sources and existing-target lookups; None means offline (CLI),
    where clones are skipped and data always creates.
    """
    gen = rule.generation
    ctx = policy_ctx.json_context

    try:
        api_version = substitute_all(ctx, gen.api_version) or gen.api_version
        kind = substitute_all(ctx, gen.kind) or gen.kind
        namespace = substitute_all(ctx, gen.namespace)
        name = substitute_all(ctx, gen.name)
        data = substitute_all(ctx, gen.data) if gen.data is not None else None
        clone = substitute_all(ctx, gen.clone) if gen.clone else None
    except VariableResolutionError as e:
        raise GenerateError(f"variable substitution failed: {e}") from e

    if clone:
        resource, mode = _manage_clone(
            api_version, kind, namespace, name, clone, client
        )
    else:
        resource, mode = _manage_data(
            api_version, kind, namespace, name, data, client
        )
    if mode == MODE_SKIP or resource is None:
        return None, MODE_SKIP

    resource = copy.deepcopy(resource)
    resource.setdefault("apiVersion", api_version)
    resource.setdefault("kind", kind)
    meta = resource.setdefault("metadata", {})
    meta["name"] = name
    if namespace:
        meta["namespace"] = namespace

    # generate.go labels.go: track provenance of the generated resource
    trigger_meta = (trigger.get("metadata") or {})
    labels = meta.setdefault("labels", {})
    labels[GENERATED_BY_LABELS["policy"]] = policy_ctx.policy.name
    labels[GENERATED_BY_LABELS["rule"]] = rule.name
    labels[GENERATED_BY_LABELS["kind"]] = trigger.get("kind", "")
    labels[GENERATED_BY_LABELS["namespace"]] = trigger_meta.get("namespace", "")
    labels[GENERATED_BY_LABELS["name"]] = trigger_meta.get("name", "")
    return resource, mode


def _manage_data(api_version, kind, namespace, name, data, client):
    """generate.go:482 manageData."""
    existing = None
    if client is not None:
        existing = client.get_resource(api_version, kind, namespace, name)
    if existing is None:
        return data, MODE_CREATE if data is not None else MODE_SKIP
    if data is None:
        return None, MODE_SKIP
    updated = copy.deepcopy(data)
    rv = ((existing.get("metadata") or {}).get("resourceVersion"))
    if rv is not None:
        updated.setdefault("metadata", {})["resourceVersion"] = rv
    return updated, MODE_UPDATE


def _manage_clone(api_version, kind, namespace, name, clone, client):
    """generate.go:504 manageClone."""
    src_namespace = clone.get("namespace", "")
    src_name = clone.get("name", "")
    if src_namespace == namespace and src_name == name:
        return None, MODE_SKIP  # self-clone
    if client is None:
        return None, MODE_SKIP  # offline: no clone source available
    source = client.get_resource(api_version, kind, src_namespace, src_name)
    if source is None:
        raise GenerateError(
            f"source resource {api_version}/{kind}/{src_namespace}/{src_name} not found"
        )
    obj = copy.deepcopy(source)
    meta = obj.setdefault("metadata", {})
    if src_namespace != namespace:
        meta.pop("ownerReferences", None)
    # scrub source-instance fields
    for field in ("uid", "selfLink", "creationTimestamp", "managedFields",
                  "resourceVersion"):
        meta.pop(field, None)

    target = client.get_resource(api_version, kind, namespace, name)
    if target is not None:
        tmeta = target.get("metadata") or {}
        for field in ("uid", "selfLink", "creationTimestamp", "managedFields",
                      "resourceVersion"):
            if field in tmeta:
                meta[field] = tmeta[field]
        if obj == target:
            return None, MODE_SKIP
        return obj, MODE_UPDATE
    return obj, MODE_CREATE
