"""Leaf comparator: scalar resource value vs scalar pattern.

Semantics mirror /root/reference/pkg/engine/validate/pattern.go and
pkg/engine/operator/operator.go:
  - operators: == (default, wildcard), ! (negated wildcard), > >= < <=,
    ranges "a-b" (inside) and "a!-b" (outside)
  - "|"-separated alternatives (OR) each of which may be "&"-joined (AND)
  - numeric-looking operands compare as k8s quantities ("1Gi" > "500Mi")
  - everything else compares as a glob wildcard over the stringified value

This module is the executable specification for the TPU leaf kernel
(kyverno_tpu/ops): the compiler decomposes each pattern through the same
parse path and emits (op, operand) lanes; results must agree everywhere.
"""

from __future__ import annotations

import re
from enum import Enum

from ..utils.gofmt import (
    convert_number_to_string,
    value_to_string_for_equality,
)
from ..utils.quantity import QuantityError, parse_quantity
from ..utils.wildcard import wildcard_match


class Op(Enum):
    EQUAL = ""
    MORE_EQUAL = ">="
    LESS_EQUAL = "<="
    NOT_EQUAL = "!"
    MORE = ">"
    LESS = "<"
    IN_RANGE = "-"
    NOT_IN_RANGE = "!-"


_NOT_IN_RANGE_RE = re.compile(r"^(\d+(\.\d+)?)([^-]*)!-(\d+(\.\d+)?)([^-]*)$")
_IN_RANGE_RE = re.compile(r"^(\d+(\.\d+)?)([^-]*)-(\d+(\.\d+)?)([^-]*)$")
_LEADING_NUMBER_RE = re.compile(r"^(\d*(\.\d+)?)(.*)", re.DOTALL)


def get_operator(pattern: str) -> Op:
    """operator.go:33 GetOperatorFromStringPattern."""
    if len(pattern) < 2:
        return Op.EQUAL
    if pattern.startswith(">="):
        return Op.MORE_EQUAL
    if pattern.startswith("<="):
        return Op.LESS_EQUAL
    if pattern.startswith(">"):
        return Op.MORE
    if pattern.startswith("<"):
        return Op.LESS
    if pattern.startswith("!"):
        return Op.NOT_EQUAL
    if _NOT_IN_RANGE_RE.match(pattern):
        return Op.NOT_IN_RANGE
    if _IN_RANGE_RE.match(pattern):
        return Op.IN_RANGE
    return Op.EQUAL


def validate_value_with_pattern(value, pattern) -> bool:
    """pattern.go:25 ValidateValueWithPattern."""
    if isinstance(pattern, bool):
        return isinstance(value, bool) and value == pattern
    if isinstance(pattern, int):
        return _validate_int(value, pattern)
    if isinstance(pattern, float):
        return _validate_float(value, pattern)
    if isinstance(pattern, str):
        return _validate_string_patterns(value, pattern)
    if pattern is None:
        return _validate_nil(value)
    if isinstance(pattern, dict):
        # existence-of-object check only, not deep equality (pattern.go:56)
        return isinstance(value, dict)
    return False  # arrays and unknown types are not valid leaf patterns


def _validate_int(value, pattern: int) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, int):
        return value == pattern
    if isinstance(value, float):
        return value == int(value) and int(value) == pattern
    if isinstance(value, str):
        try:
            return int(value, 10) == pattern
        except ValueError:
            return False
    return False


def _validate_float(value, pattern: float) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, int):
        return pattern == int(pattern) and int(pattern) == value
    if isinstance(value, float):
        return value == pattern
    if isinstance(value, str):
        try:
            return float(value) == pattern
        except ValueError:
            return False
    return False


def _validate_nil(value) -> bool:
    if isinstance(value, bool):
        return not value
    if isinstance(value, float):
        return value == 0.0
    if isinstance(value, int):
        return value == 0
    if isinstance(value, str):
        return value == ""
    if value is None:
        return True
    return False


def _validate_string_patterns(value, pattern: str) -> bool:
    """OR over "|" alternatives, AND over "&" within each (pattern.go:153)."""
    for alternative in pattern.split("|"):
        alternative = alternative.strip(" ")
        if _check_and_conditions(value, alternative):
            return True
    return False


def _check_and_conditions(value, pattern: str) -> bool:
    for condition in pattern.split("&"):
        if not validate_string_pattern(value, condition.strip(" ")):
            return False
    return True


def validate_string_pattern(value, pattern: str) -> bool:
    """Single operator-prefixed pattern (pattern.go:177)."""
    op = get_operator(pattern)

    if op is Op.IN_RANGE:
        left, right = pattern.split("-")[0], pattern.split("-")[1]
        return validate_string_pattern(value, f">={left}") and validate_string_pattern(
            value, f"<={right}"
        )
    if op is Op.NOT_IN_RANGE:
        left, right = pattern.split("!-")[0], pattern.split("!-")[1]
        return validate_string_pattern(value, f"<{left}") or validate_string_pattern(
            value, f">{right}"
        )

    body = pattern[len(op.value):].strip()
    number, rest = _split_leading_number(body)
    if number == "":
        return _validate_string(value, rest, op)
    return _validate_number_with_str(value, body, op)


def _split_leading_number(pattern: str) -> tuple[str, str]:
    m = _LEADING_NUMBER_RE.match(pattern)
    return m.group(1), m.group(3)


def _validate_string(value, pattern: str, op: Op) -> bool:
    """Wildcard equality for non-numeric operands (pattern.go:210)."""
    if op not in (Op.EQUAL, Op.NOT_EQUAL):
        return False  # >, >=, <, <= are not applicable to strings
    s = value_to_string_for_equality(value)
    if s is None:
        return False
    result = wildcard_match(pattern, s)
    return (not result) if op is Op.NOT_EQUAL else result


def _validate_number_with_str(value, pattern: str, op: Op) -> bool:
    """Quantity comparison if the operand parses as one, else wildcard
    (pattern.go:263)."""
    s = convert_number_to_string(value)
    if s is None:
        return False
    try:
        pattern_q = parse_quantity(pattern)
    except QuantityError:
        return wildcard_match(pattern, s)
    try:
        value_q = parse_quantity(s)
    except QuantityError:
        return False
    if value_q < pattern_q:
        cmp = -1
    elif value_q > pattern_q:
        cmp = 1
    else:
        cmp = 0
    if op is Op.EQUAL:
        return cmp == 0
    if op is Op.NOT_EQUAL:
        return cmp != 0
    if op is Op.MORE:
        return cmp > 0
    if op is Op.LESS:
        return cmp < 0
    if op is Op.MORE_EQUAL:
        return cmp >= 0
    if op is Op.LESS_EQUAL:
        return cmp <= 0
    return False
