"""Generic JSON traversal applying an action at leaves and map keys,
tracking the element path (mirrors /root/reference/pkg/engine/jsonutils).

As in the reference (traverse.go:62-78), the action's RESULT is traversed
further: a leaf that substitutes into a container has its own leaves
processed too. A map key that substitutes to a non-string is an error
(traverse.go:100)."""

from __future__ import annotations

from typing import Callable

# action(element, path, document) -> new element; raise to abort
Action = Callable[[object, str, object], object]


class NonStringKeyError(ValueError):
    def __init__(self, path: str):
        super().__init__(
            f"expected string after substituting variables in key at path {path}"
        )


def traverse_leaves_and_keys(document, action: Action):
    """Rebuilds the document, applying ``action`` to every scalar leaf and
    every map key (a changed key renames the entry)."""

    def walk(element, path):
        if not isinstance(element, (dict, list)):
            element = action(element, path, document)
        if isinstance(element, dict):
            out = {}
            for k, v in element.items():
                new_key = action(k, path, document)
                if not isinstance(new_key, str):
                    raise NonStringKeyError(path)
                out[new_key] = walk(v, f"{path}/{k}")
            return out
        if isinstance(element, list):
            return [walk(v, f"{path}/{i}") for i, v in enumerate(element)]
        return element

    return walk(document, "")
