"""Kubernetes API URL-path parser for APICall context entries.

Mirrors /root/reference/pkg/engine/apiPath.go (NewAPIPath). Paths follow
https://kubernetes.io/docs/reference/using-api/api-concepts/:

  /api/v1/RESOURCE[/NAME]                     core group, cluster scope
  /api/v1/namespaces/NS/RESOURCE[/NAME]       core group, namespaced
  /apis/GROUP/VERSION/RESOURCE[/NAME]
  /apis/GROUP/VERSION/namespaces/NS/RESOURCE[/NAME]
"""

from __future__ import annotations

from dataclasses import dataclass


class APIPathError(ValueError):
    pass


@dataclass
class APIPath:
    root: str = ""
    group: str = ""
    version: str = ""
    resource_type: str = ""
    name: str = ""
    namespace: str = ""

    @property
    def api_version(self) -> str:
        """group/version string as used by dynamic clients ("v1" for core)."""
        if self.root == "api":
            return self.group  # core group: the segment after /api is the version
        return f"{self.group}/{self.version}"

    def __str__(self) -> str:
        parts = [self.root]
        if self.root == "api":
            parts.append(self.group)
        else:
            parts.extend([self.group, self.version])
        if self.namespace:
            parts.extend(["namespaces", self.namespace])
        parts.append(self.resource_type)
        if self.name:
            parts.append(self.name)
        return "/" + "/".join(parts)


def parse_api_path(path: str) -> APIPath:
    """apiPath.go:19 NewAPIPath."""
    trimmed = path.strip().strip("/")
    paths = trimmed.split("/")

    if len(paths) < 3 or len(paths) > 7:
        raise APIPathError(f"invalid path length {path}")
    if paths[0] not in ("api", "apis"):
        raise APIPathError("urlPath must start with /api or /apis")
    if paths[0] == "api" and paths[1] != "v1":
        raise APIPathError("expected urlPath to start with /api/v1/")

    if paths[0] == "api":
        if len(paths) == 3:
            return APIPath(root=paths[0], group=paths[1], resource_type=paths[2])
        if len(paths) == 4:
            return APIPath(
                root=paths[0], group=paths[1], resource_type=paths[2], name=paths[3]
            )
        if len(paths) == 5:
            return APIPath(
                root=paths[0], group=paths[1], namespace=paths[3], resource_type=paths[4]
            )
        if len(paths) == 6:
            return APIPath(
                root=paths[0],
                group=paths[1],
                namespace=paths[3],
                resource_type=paths[4],
                name=paths[5],
            )
        raise APIPathError(f"invalid API v1 path {path}")

    if len(paths) == 4:
        return APIPath(
            root=paths[0], group=paths[1], version=paths[2], resource_type=paths[3]
        )
    if len(paths) == 5:
        return APIPath(
            root=paths[0],
            group=paths[1],
            version=paths[2],
            resource_type=paths[3],
            name=paths[4],
        )
    if len(paths) == 6:
        return APIPath(
            root=paths[0],
            group=paths[1],
            version=paths[2],
            namespace=paths[4],
            resource_type=paths[5],
        )
    if len(paths) == 7:
        return APIPath(
            root=paths[0],
            group=paths[1],
            version=paths[2],
            namespace=paths[4],
            resource_type=paths[5],
            name=paths[6],
        )
    raise APIPathError(f"invalid API path {path}")
