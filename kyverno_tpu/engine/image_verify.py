"""Image verification engine (verifyImages rules).

Mirrors /root/reference/pkg/engine/imageVerify.go:21-251
(VerifyAndPatchImages / verifySignature / patchDigest / attestImage /
checkAttestations): per matching rule, every container image matching the
rule's image pattern is either signature-verified — passing images get
their reference patched to digest form (makeAddDigestPatch,
imageVerify.go:209) — or checked against in-toto attestation predicates
with any/all conditions evaluated over the statement's predicate plus an
``image`` context object (imageVerify.go:217-251).

The reference's cosign/OCI-registry client (pkg/cosign/cosign.go) is a
network service client, not engine logic; here it is a pluggable
:class:`Verifier` seam. :class:`StaticVerifier` implements the same trust
decision (key -> signed digest, image -> attestation statements) from a
declared store — the CLI mock-store pattern (pkg/kyverno/store) applied to
signatures — and is also what tests and air-gapped deployments use.
"""

from __future__ import annotations

import copy
import re
import time
from dataclasses import dataclass, field

from .context import Context, image_string
from .policy_context import PolicyContext
from .response import (
    EngineResponse,
    PolicySpecSummary,
    ResourceSpec,
    RuleResponse,
    RuleStatus,
    RuleType,
)
from .json_context_loader import ContextLoadError, load_context
from .operators import evaluate_condition, evaluate_conditions
from .validation import _matches
from .variables import VariableResolutionError, substitute_all
from ..utils.wildcard import wildcard_match


class VerificationError(Exception):
    """Signature/attestation verification failure (cosign.Verify error)."""


class Verifier:
    """The seam the engine calls for the actual trust decision.

    ``verify_signature`` returns the verified digest or raises
    :class:`VerificationError` (cosign.VerifySignature,
    pkg/cosign/cosign.go:30); ``fetch_attestations`` returns in-toto
    statement dicts (cosign.FetchAttestations, cosign.go:103)."""

    def verify_signature(self, image: str, key: str = "", repository: str = "",
                         roots: str = "", subject: str = "") -> str:
        raise VerificationError("no image verifier configured")

    def fetch_attestations(self, image: str, key: str = "",
                           repository: str = "", roots: str = "",
                           subject: str = "") -> list[dict]:
        raise VerificationError("no image verifier configured")


@dataclass
class SignedImage:
    digest: str
    keys: list[str] = field(default_factory=list)   # public keys / key ids


@dataclass
class StaticVerifier(Verifier):
    """Trust store for tests, CLI runs and air-gapped clusters: a map of
    image reference -> (digest, accepted keys) and image -> statements."""

    signed: dict = field(default_factory=dict)        # image -> SignedImage
    statements: dict = field(default_factory=dict)    # image -> [statement]

    def sign(self, image: str, digest: str, key: str = "") -> None:
        entry = self.signed.setdefault(image, SignedImage(digest=digest))
        entry.digest = digest
        if key:
            entry.keys.append(key)

    def attest(self, image: str, statement: dict) -> None:
        self.statements.setdefault(image, []).append(statement)

    def verify_signature(self, image: str, key: str = "", repository: str = "",
                         roots: str = "", subject: str = "") -> str:
        entry = self.signed.get(image)
        if entry is None:
            raise VerificationError(f"no signature found for {image}")
        if key and entry.keys and key not in entry.keys:
            raise VerificationError(f"signature key mismatch for {image}")
        return entry.digest

    def fetch_attestations(self, image: str, key: str = "",
                           repository: str = "", roots: str = "",
                           subject: str = "") -> list[dict]:
        if image not in self.statements:
            raise VerificationError(f"no attestations found for {image}")
        return list(self.statements[image])


_POINTER_INDEX = re.compile(r"/(\d+)(?=/|$)")


def json_pointer_to_jmespath(pointer: str) -> str:
    """utils.JsonPointerToJMESPath: /spec/containers/0/image ->
    spec.containers[0].image."""
    s = _POINTER_INDEX.sub(r"[\1]", pointer)
    return s.strip("/").replace("/", ".")


def _rule_response(rule, msg: str, status: RuleStatus,
                   rtype: RuleType = RuleType.IMAGE_VERIFY) -> RuleResponse:
    return RuleResponse(name=rule.name, type=rtype, message=msg, status=status)


def verify_and_patch_images(policy_ctx: PolicyContext,
                            verifier: Verifier) -> EngineResponse:
    """imageVerify.go:21 VerifyAndPatchImages."""
    start = time.monotonic()
    resp = EngineResponse(patched_resource=policy_ctx.new_resource)
    resource = policy_ctx.new_resource or {}
    meta = resource.get("metadata") or {}
    resp.policy_response.policy = PolicySpecSummary(
        name=policy_ctx.policy.name if policy_ctx.policy else "",
        validation_failure_action=(
            policy_ctx.policy.spec.validation_failure_action
            if policy_ctx.policy else "audit"),
    )
    resp.policy_response.resource = ResourceSpec(
        kind=resource.get("kind", ""),
        api_version=resource.get("apiVersion", ""),
        namespace=meta.get("namespace", ""),
        name=meta.get("name", ""),
        uid=meta.get("uid", ""),
    )

    ctx = policy_ctx.json_context
    images = ctx.images if ctx is not None else None
    if not images:
        return resp

    ctx.checkpoint()
    try:
        for rule in policy_ctx.policy.spec.rules:
            if not rule.has_verify_images():
                continue
            if not _matches(rule, policy_ctx):
                continue
            ctx.restore()
            ctx.checkpoint()

            try:
                load_context(rule.context, policy_ctx, rule.name)
            except ContextLoadError as e:
                resp.policy_response.rules.append(_rule_response(
                    rule, f"failed to load context: {e}", RuleStatus.ERROR))
                continue

            for iv in rule.verify_images:
                # variables substitute in the spec fields but NOT in
                # attestations (imageVerify.go:90 substituteVariables)
                try:
                    spec = substitute_all(ctx, {
                        "image": iv.image, "key": iv.key, "roots": iv.roots,
                        "subject": iv.subject, "repository": iv.repository,
                    })
                except VariableResolutionError as e:
                    resp.policy_response.rules.append(_rule_response(
                        rule, f"failed to substitute variables: {e}",
                        RuleStatus.ERROR))
                    continue
                for bucket in ("containers", "initContainers"):
                    _verify_bucket(resp, policy_ctx, rule, spec,
                                   iv.attestations, verifier,
                                   images.get(bucket) or {})
    finally:
        ctx.restore()

    resp.policy_response.processing_time_s = time.monotonic() - start
    return resp


def _verify_bucket(resp, policy_ctx, rule, spec, attestations, verifier,
                   infos: dict) -> None:
    """imageVerifier.verify (imageVerify.go:117)."""
    ctx = policy_ctx.json_context
    for info in infos.values():
        image = image_string(info)

        # UPDATE requests skip unchanged images (imageVerify.go:124)
        pointer = info.get("jsonPath", "")
        if pointer:
            try:
                if not ctx.has_changed(json_pointer_to_jmespath(pointer)):
                    continue
            except Exception:
                pass  # HasChanged error -> proceed (err != nil branch)

        if not wildcard_match(spec["image"], image):
            continue

        if not attestations:
            rule_resp, digest = _verify_signature(rule, spec, image, verifier)
            if rule_resp.status == RuleStatus.PASS and not info.get("digest"):
                # makeAddDigestPatch (imageVerify.go:209)
                rule_resp.patches = [{
                    "op": "replace",
                    "path": pointer,
                    "value": image + "@" + digest,
                }]
        else:
            rule_resp = _attest_image(policy_ctx, rule, spec, info,
                                      attestations, verifier)
        resp.policy_response.rules.append(rule_resp)


def _verify_signature(rule, spec, image: str, verifier) -> tuple[RuleResponse, str]:
    """imageVerify.go:160 verifySignature. The reference tags these rule
    responses with the Validation type (not ImageVerify) — mirrored."""
    try:
        digest = verifier.verify_signature(
            image, key=spec["key"], repository=spec["repository"],
            roots=spec["roots"], subject=spec["subject"])
    except VerificationError as e:
        return _rule_response(
            rule, f"image signature verification failed for {image}: {e}",
            RuleStatus.FAIL, RuleType.VALIDATION), ""
    return _rule_response(rule, f"image {image} verified",
                          RuleStatus.PASS, RuleType.VALIDATION), digest


def _attest_image(policy_ctx, rule, spec, info, attestations,
                  verifier) -> RuleResponse:
    """imageVerify.go:217 attestImage + :251 checkAttestations."""
    image = image_string(info)
    try:
        statements = verifier.fetch_attestations(
            image, key=spec["key"], repository=spec["repository"],
            roots=spec["roots"], subject=spec["subject"])
    except VerificationError as e:
        return _rule_response(
            rule, f"failed to fetch attestations for {image}: {e}",
            RuleStatus.ERROR)

    for check in attestations:
        want_type = check.get("predicateType")
        for statement in statements:
            if statement.get("predicateType") != want_type:
                continue
            try:
                ok = _check_attestation(policy_ctx, check, statement, info)
            except Exception as e:
                return _rule_response(
                    rule, f"error while checking attestation: {e}",
                    RuleStatus.ERROR)
            if not ok:
                return _rule_response(
                    rule,
                    f"attestation checks failed for {image} and predicate "
                    f"{want_type}", RuleStatus.FAIL)
    return _rule_response(rule, f"attestation checks passed for {image}",
                          RuleStatus.PASS)


def _check_attestation(policy_ctx, check: dict, statement: dict, info) -> bool:
    """checkAttestations: conditions evaluate over the statement's
    predicate merged with an ``image`` object (imageVerify.go:251-299)."""
    conditions = check.get("conditions")
    if not conditions:
        return True

    ctx = policy_ctx.json_context
    predicate = statement.get("predicate")
    if not isinstance(predicate, dict):
        raise ValueError(f"failed to extract predicate from statement: "
                         f"{statement}")

    ctx.checkpoint()
    try:
        ctx.add_json(copy.deepcopy(predicate))
        ctx.add_json({"image": {
            "image": image_string(info),
            "registry": info.get("registry", ""),
            "path": info.get("path", ""),
            "name": info.get("name", ""),
            "tag": info.get("tag", ""),
            "digest": info.get("digest", ""),
        }})
        substituted = substitute_all(ctx, copy.deepcopy(conditions))
        # Attestation.Conditions is a []AnyAllConditions: every block must
        # pass (variables/evaluate.go:11 EvaluateAnyAllConditions)
        if isinstance(substituted, list) and substituted and all(
                isinstance(b, dict) and set(b) <= {"any", "all"}
                for b in substituted):
            return all(evaluate_conditions(b) for b in substituted)
        return evaluate_conditions(substituted)
    finally:
        ctx.restore()
