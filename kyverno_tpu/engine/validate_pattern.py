"""Recursive JSON-tree pattern matcher with anchor semantics.

The executable specification for the TPU pattern NFA: semantics mirror
/root/reference/pkg/engine/validate/validate.go element-for-element.
Outcome is a tri-state: matched / failed(path) / skip (a conditional or
global anchor did not apply, so the rule does not apply to the resource).
"""

from __future__ import annotations

from dataclasses import dataclass

from .anchors import (
    Anchor,
    anchor_kind,
    remove_anchor,
    split_anchors,
    has_nested_anchors,
)
from .pattern import validate_value_with_pattern
from .wildcards import expand_in_metadata

_SCALAR = (str, int, float, bool)


@dataclass
class PatternResult:
    matched: bool
    skip: bool = False
    path: str = ""
    message: str = ""


class _Mismatch(Exception):
    def __init__(self, path: str, message: str):
        super().__init__(message)
        self.path = path
        self.message = message


class _ConditionalAnchorMismatch(_Mismatch):
    pass


class _GlobalAnchorMismatch(_Mismatch):
    pass


class _AnchorTracker:
    """Tracks whether condition/existence/negation anchor keys from the
    pattern ever exist in the resource (common/anchor_key.go). If a tracked
    anchor key never appears, a failure reports as 'missing anchor' with an
    empty path — outcome is still FAIL, not SKIP."""

    def __init__(self):
        self.anchor_map: dict[str, bool] = {}

    def check(self, pattern_map: dict, resource) -> None:
        for key in pattern_map:
            kind = anchor_kind(key)
            if kind in (Anchor.CONDITION, Anchor.EXISTENCE, Anchor.NEGATION):
                if self.anchor_map.get(key):
                    continue
                self.anchor_map.setdefault(key, False)
                if self._key_in(key, resource):
                    self.anchor_map[key] = True

    @staticmethod
    def _key_in(key: str, resource) -> bool:
        bare, _ = remove_anchor(key)
        if isinstance(resource, dict):
            return bare in resource
        if isinstance(resource, list):
            return any(
                isinstance(el, dict) and bare in el for el in resource
            )
        return False

    def is_anchor_error(self) -> bool:
        return any(not v for v in self.anchor_map.values())


def match_pattern(resource, pattern) -> PatternResult:
    """validate.go:29 MatchPattern. Root entry; path starts at "/"."""
    ac = _AnchorTracker()
    try:
        _validate_element(resource, pattern, pattern, "/", ac)
    except (_ConditionalAnchorMismatch, _GlobalAnchorMismatch) as e:
        return PatternResult(False, skip=True, path="", message=e.message)
    except _Mismatch as e:
        if ac.is_anchor_error():
            return PatternResult(False, skip=False, path="", message=e.message)
        return PatternResult(False, skip=False, path=e.path, message=e.message)
    return PatternResult(True)


def _validate_element(resource, pattern, origin, path: str, ac: _AnchorTracker) -> None:
    """validate.go:55 validateResourceElement."""
    if isinstance(pattern, dict):
        if not isinstance(resource, dict):
            raise _Mismatch(
                path,
                f"pattern and resource have different structures at path {path}: "
                f"expected object, found {type(resource).__name__}",
            )
        ac.check(pattern, resource)
        _validate_map(resource, pattern, origin, path, ac)
    elif isinstance(pattern, list):
        if not isinstance(resource, list):
            raise _Mismatch(
                path,
                f"validation rule failed at path {path}: resource does not "
                "satisfy the expected overlay pattern",
            )
        _validate_array(resource, pattern, origin, path, ac)
    elif pattern is None or isinstance(pattern, _SCALAR):
        if isinstance(resource, list):
            for el in resource:
                if not validate_value_with_pattern(el, pattern):
                    raise _Mismatch(
                        path,
                        f"resource value {resource!r} does not match "
                        f"{pattern!r} at path {path}",
                    )
        elif not validate_value_with_pattern(resource, pattern):
            raise _Mismatch(
                path,
                f"resource value {resource!r} does not match {pattern!r} "
                f"at path {path}",
            )
    else:
        raise _Mismatch(path, f"failed at {path}: pattern contains unknown type")


def _validate_map(resource_map: dict, pattern_map: dict, origin, path: str, ac: _AnchorTracker) -> None:
    """validate.go:102 validateMap: anchors evaluate first, then the rest
    (nested-anchor-bearing values ahead of plain ones)."""
    pattern_map = expand_in_metadata(pattern_map, resource_map)
    anchors, rest = split_anchors(pattern_map)

    for key, pattern_el in anchors.items():
        _handle_anchor(key, pattern_el, resource_map, origin, path, ac)

    rest_keys = sorted(rest, key=lambda k: not has_nested_anchors(rest[k]))
    for key in rest_keys:
        _handle_anchor(key, rest[key], resource_map, origin, path, ac)


def _handle_anchor(key: str, pattern_el, resource_map: dict, origin, path: str, ac: _AnchorTracker) -> None:
    """anchor/anchor.go:21 CreateElementHandler dispatch."""
    kind = anchor_kind(key)
    bare, _ = remove_anchor(key)
    current = f"{path}{bare}/"

    if kind is Anchor.CONDITION:
        if bare in resource_map:
            try:
                _validate_element(resource_map[bare], pattern_el, origin, current, ac)
            except _Mismatch as e:
                raise _ConditionalAnchorMismatch(e.path, f"conditional anchor mismatch: {e.message}")
        return

    if kind is Anchor.GLOBAL:
        if bare in resource_map:
            try:
                _validate_element(resource_map[bare], pattern_el, origin, current, ac)
            except _Mismatch as e:
                raise _GlobalAnchorMismatch(e.path, f"global anchor mismatch: {e.message}")
        return

    if kind is Anchor.EQUALITY:
        if bare in resource_map:
            _validate_element(resource_map[bare], pattern_el, origin, current, ac)
        return

    if kind is Anchor.NEGATION:
        if bare in resource_map:
            raise _Mismatch(current, f"{current}{bare} is not allowed")
        return

    if kind is Anchor.EXISTENCE:
        if bare in resource_map:
            value = resource_map[bare]
            if not isinstance(value, list):
                raise _Mismatch(
                    current,
                    "existence anchor ^() can be used only on list-type resources",
                )
            if not isinstance(pattern_el, list):
                raise _Mismatch(current, "existence anchor pattern must be a list")
            for pat in pattern_el:
                if not isinstance(pat, dict):
                    raise _Mismatch(
                        current, "existence anchor pattern elements must be maps"
                    )
                _validate_existence(value, pat, origin, current, ac)
        return

    # default handler (anchor.go:105): "*" means key must exist and be non-null
    if pattern_el == "*" and resource_map.get(bare) is not None:
        return
    if pattern_el == "*" and resource_map.get(bare) is None:
        raise _Mismatch(path, f"{path}{bare} not found")
    _validate_element(resource_map.get(bare), pattern_el, origin, current, ac)


def _validate_existence(resource_list: list, pattern_map: dict, origin, path: str, ac: _AnchorTracker) -> None:
    """At least one list element matches the pattern map (anchor.go:262)."""
    for i, el in enumerate(resource_list):
        try:
            _validate_element(el, pattern_map, origin, f"{path}{i}/", ac)
            return
        except _Mismatch:
            continue
    raise _Mismatch(path, f"existence anchor validation failed at path {path}")


def _validate_array(resource_array: list, pattern_array: list, origin, path: str, ac: _AnchorTracker) -> None:
    """validate.go:140 validateArray."""
    if not pattern_array:
        raise _Mismatch(path, "pattern array is empty")

    head = pattern_array[0]
    if isinstance(head, dict):
        # every resource element must match the (single) pattern map, except
        # elements a conditional anchor excludes (validate.go:180)
        for i, el in enumerate(resource_array):
            try:
                _validate_element(el, head, origin, f"{path}{i}/", ac)
            except _ConditionalAnchorMismatch:
                continue
    elif head is None or isinstance(head, _SCALAR):
        _validate_element(resource_array, head, origin, path, ac)
    else:
        if len(resource_array) < len(pattern_array):
            raise _Mismatch(
                path,
                f"validate array failed: resource has {len(resource_array)} "
                f"elements, pattern expects {len(pattern_array)}",
            )
        for i, pattern_el in enumerate(pattern_array):
            try:
                _validate_element(resource_array[i], pattern_el, origin, f"{path}{i}/", ac)
            except _ConditionalAnchorMismatch:
                continue
