"""PolicyContext — the single input struct to every engine entry point.

Mirrors /root/reference/pkg/engine/policyContext.go:12-60. ``client`` is any
object exposing ``get_resource(api_version, kind, namespace, name)`` /
``list_resource(api_version, kind, namespace)`` / ``get_configmap(namespace,
name)`` — a live cluster client, a snapshot store, or None for offline runs.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..api.types import ClusterPolicy
from .context import Context
from .match import RequestInfo


@dataclass
class PolicyContext:
    policy: ClusterPolicy = field(default_factory=ClusterPolicy)
    new_resource: dict = field(default_factory=dict)
    old_resource: dict = field(default_factory=dict)
    element: Optional[dict] = None                     # foreach loop element
    admission_info: RequestInfo = field(default_factory=RequestInfo)
    exclude_group_role: list[str] = field(default_factory=list)
    exclude_resource_func: Optional[Callable[[str, str, str], bool]] = None
    client: Any = None
    resource_cache: Any = None  # pkg/resourcecache seam: cached listers for
    # ConfigMap context entries; falls back to ``client`` when absent
    json_context: Context = field(default_factory=Context)
    namespace_labels: dict[str, str] = field(default_factory=dict)

    def copy(self) -> "PolicyContext":
        """policyContext.go Copy: shallow copy sharing the JSON context, so
        foreach iterations see checkpoint/restore effects (validation.go:236)."""
        c = copy.copy(self)
        return c

    def excluded_by_func(self, kind: str, namespace: str, name: str) -> bool:
        if self.exclude_resource_func is None:
            return False
        return self.exclude_resource_func(kind, namespace, name)
