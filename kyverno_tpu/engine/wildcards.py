"""Wildcard expansion helpers for metadata maps and label selectors.

Mirrors /root/reference/pkg/engine/wildcards/wildcards.go: validation
patterns may use globs in metadata.labels / metadata.annotations *keys*;
before matching, such keys are replaced by the first matching concrete key
from the resource (values are matched later by the normal leaf comparator).
"""

from __future__ import annotations

from .anchors import add_anchor, remove_anchor
from ..utils.wildcard import has_wildcards, wildcard_match


def replace_in_selector(match_labels: dict, resource_labels: dict) -> dict:
    """Expand wildcard keys AND values in a labelSelector.matchLabels map
    against the resource's labels (wildcards.go:14)."""
    result = {}
    for k, v in match_labels.items():
        if has_wildcards(k) or has_wildcards(str(v)):
            nk, nv = _expand(k, str(v), resource_labels, match_value=True, replace=True)
            result[nk] = nv
        else:
            result[k] = v
    return result


def _expand(k: str, v: str, resource_map: dict, match_value: bool, replace: bool):
    for rk, rv in resource_map.items():
        if wildcard_match(k, rk):
            if not match_value:
                return rk, rv
            if wildcard_match(v, str(rv)):
                return rk, rv
    if replace:
        k = k.replace("*", "0").replace("?", "0")
        v = v.replace("*", "0").replace("?", "0")
    return k, v


def expand_in_metadata(pattern_map: dict, resource_map: dict) -> dict:
    """Expand wildcard keys under pattern metadata.labels/annotations using
    the resource's concrete keys (wildcards.go:69). Anchors on the keys are
    preserved. Returns a (possibly new) pattern map; never mutates input."""
    meta_key, pattern_meta = _get_anchored(pattern_map, "metadata")
    if not isinstance(pattern_meta, dict):
        return pattern_map
    resource_meta = resource_map.get("metadata")
    if not isinstance(resource_meta, dict):
        return pattern_map

    new_meta = dict(pattern_meta)
    changed = False
    for tag in ("labels", "annotations"):
        pkey, pdata = _get_anchored(pattern_meta, tag)
        if not isinstance(pdata, dict):
            continue
        _, rdata = _get_anchored(resource_meta, tag)
        if not isinstance(rdata, dict):
            continue
        expanded = {}
        for k, v in pdata.items():
            if has_wildcards(k):
                bare, prefix = remove_anchor(k)
                nk, _ = _expand(bare, str(v), rdata, match_value=False, replace=False)
                if prefix:
                    nk = add_anchor(nk, prefix)
                expanded[nk] = v
            else:
                expanded[k] = v
        new_meta[pkey] = expanded
        changed = True

    if not changed:
        return pattern_map
    out = dict(pattern_map)
    out[meta_key] = new_meta
    return out


def _get_anchored(m: dict, tag: str):
    """Find key equal to ``tag`` modulo anchor decoration."""
    for k, v in m.items():
        if remove_anchor(k)[0] == tag:
            return k, v
    return "", None
