"""Pattern-key anchors: modifiers that change match semantics for a map key.

Kinds (cf. /root/reference/pkg/engine/anchor/common/common.go):
  - condition  ``(key)``   : if key matches, rest of map must match; if the
                             key's own pattern mismatches -> SKIP the rule
  - global     ``<(key)``  : like condition, but mismatch skips the whole rule
                             from anywhere in the tree
  - existence  ``^(key)``  : at least one element of the resource list matches
  - equality   ``=(key)``  : if key present in resource, value must match
  - negation   ``X(key)``  : key must NOT be present in resource
  - addition   ``+(key)``  : mutate-only; add if not present
"""

from __future__ import annotations

from enum import Enum
from functools import lru_cache


class Anchor(Enum):
    NONE = ""
    CONDITION = "("
    GLOBAL = "<("
    EXISTENCE = "^("
    EQUALITY = "=("
    NEGATION = "X("
    ADD_IF_NOT_PRESENT = "+("


def is_condition_anchor(key: str) -> bool:
    return len(key) >= 2 and key[0] == "(" and key[-1] == ")"


def _is_prefixed(key: str, prefix: str) -> bool:
    return len(key) >= len(prefix) + 1 and key.startswith(prefix) and key.endswith(")")


def is_global_anchor(key: str) -> bool:
    return _is_prefixed(key, "<(")


def is_existence_anchor(key: str) -> bool:
    return _is_prefixed(key, "^(")


def is_equality_anchor(key: str) -> bool:
    return _is_prefixed(key, "=(")


def is_negation_anchor(key: str) -> bool:
    return _is_prefixed(key, "X(")


def is_addition_anchor(key: str) -> bool:
    return _is_prefixed(key, "+(")


def is_non_anchor(key: str) -> bool:
    return anchor_kind(key) is Anchor.NONE


@lru_cache(maxsize=4096)
def anchor_kind(key: str) -> Anchor:
    # two-char prefixes take precedence over the bare "(...)" form; a pure
    # function of the key string, and the oracle asks it ~56k times per
    # library-corpus admission over a small recurring key set — memoized
    if is_global_anchor(key):
        return Anchor.GLOBAL
    if is_existence_anchor(key):
        return Anchor.EXISTENCE
    if is_equality_anchor(key):
        return Anchor.EQUALITY
    if is_negation_anchor(key):
        return Anchor.NEGATION
    if is_addition_anchor(key):
        return Anchor.ADD_IF_NOT_PRESENT
    if is_condition_anchor(key):
        return Anchor.CONDITION
    return Anchor.NONE


def remove_anchor(key: str) -> tuple[str, str]:
    """Strip the anchor decoration: returns (bare key, anchor prefix)."""
    kind = anchor_kind(key)
    if kind is Anchor.NONE:
        return key, ""
    return key[len(kind.value):-1], kind.value


def add_anchor(key: str, prefix: str) -> str:
    return f"{prefix}{key})"


def remove_anchors_from_path(path: str) -> str:
    parts = [p for p in path.split("/") if p != ""]
    cleaned = "/".join(remove_anchor(p)[0] for p in parts)
    return ("/" + cleaned) if path.startswith("/") else cleaned


def split_anchors(pattern_map: dict) -> tuple[dict, dict]:
    """Two-phase split used by the map matcher (anchor/anchor.go:265):
    condition/existence/equality/negation anchors evaluate first, the rest
    after. Global anchors intentionally stay in the 'rest' bucket, matching
    the reference (they are still handled by their own handler)."""
    anchors, rest = {}, {}
    for key, value in pattern_map.items():
        kind = anchor_kind(key)
        if kind in (Anchor.CONDITION, Anchor.EXISTENCE, Anchor.EQUALITY, Anchor.NEGATION):
            anchors[key] = value
        else:
            rest[key] = value
    return anchors, rest


def has_nested_anchors(pattern) -> bool:
    """True if any key anywhere under ``pattern`` carries an anchor."""
    if isinstance(pattern, dict):
        for k, v in pattern.items():
            if anchor_kind(k) is not Anchor.NONE or has_nested_anchors(v):
                return True
        return False
    if isinstance(pattern, list):
        return any(has_nested_anchors(v) for v in pattern)
    return False
