"""External-data context loading (rule ``context:`` entries).

Mirrors /root/reference/pkg/engine/jsonContext.go: ConfigMap entries come
from the cluster client's configmap store, APICall entries GET/LIST against
the API, and in mock mode (CLI / tests) every entry resolves from the
declared values in :mod:`kyverno_tpu.store`.
"""

from __future__ import annotations

import json

from .. import store
from .api_path import parse_api_path
from .context import Context
from .policy_context import PolicyContext
from .variables import substitute_all


class ContextLoadError(Exception):
    pass


def load_context(context_entries: list, policy_ctx: PolicyContext, rule_name: str) -> None:
    """jsonContext.go:21 LoadContext."""
    if not context_entries:
        return

    policy_name = policy_ctx.policy.name
    if store.get_mock():
        _load_mock_context(policy_name, rule_name, policy_ctx.json_context)
        return

    for entry in context_entries:
        if entry.config_map is not None:
            _load_config_map(entry, policy_ctx)
        elif entry.api_call is not None:
            _load_api_data(entry, policy_ctx)


def _load_mock_context(policy_name: str, rule_name: str, ctx: Context) -> None:
    """jsonContext.go:27-48 mock branch: declared values become context
    entries; multiline strings split into lists unless PEM."""
    rule = store.get_policy_rule_from_context(policy_name, rule_name)
    if rule is None or not rule.values:
        raise ContextLoadError(
            f"No values found for policy {policy_name} rule {rule_name}"
        )

    for key, value in rule.values.items():
        if isinstance(value, str):
            trimmed = value.strip("\n")
            if "\n" in trimmed:
                value = parse_multiline_block_body({key: value})[key]
        ctx.add_json(variable_to_json(key, value))


def variable_to_json(key: str, value) -> dict:
    """pkg/common VariableToJSON: dotted keys nest ("a.b.c" -> {a:{b:{c:v}}});
    JSON-looking string values parse structurally."""
    if isinstance(value, str):
        stripped = value.strip()
        if stripped[:1] in ("{", "["):
            try:
                value = json.loads(stripped)
            except json.JSONDecodeError:
                pass
    path = key.split(".")
    doc = value
    for segment in reversed(path):
        doc = {segment: doc}
    return doc


def _load_config_map(entry, policy_ctx: PolicyContext) -> None:
    """jsonContext.go:189 loadConfigMap + fetchConfigMap."""
    ctx = policy_ctx.json_context
    name = substitute_all(ctx, entry.config_map.get("name", ""))
    namespace = substitute_all(ctx, entry.config_map.get("namespace", "")) or "default"

    source = policy_ctx.resource_cache or policy_ctx.client
    if source is None:
        raise ContextLoadError("configmap client is not available")
    obj = source.get_configmap(namespace, name)
    if obj is None:
        raise ContextLoadError(
            f"failed to read configmap {namespace}/{name} from cache"
        )

    data = parse_multiline_block_body(dict(obj.get("data") or {}))
    ctx.add_json(
        {entry.name: {"data": data, "metadata": obj.get("metadata") or {}}}
    )


def _load_api_data(entry, policy_ctx: PolicyContext) -> None:
    """jsonContext.go:74 loadAPIData: fetch, optional JMESPath reduction."""
    ctx = policy_ctx.json_context
    data = _fetch_api_data(entry, policy_ctx)

    jmespath_expr = (entry.api_call or {}).get("jmesPath", "")
    if not jmespath_expr:
        if not isinstance(data, dict):
            raise ContextLoadError(
                f"failed to add resource data to context: contextEntry {entry.name}"
            )
        ctx.add_json(data)
        return

    path = substitute_all(ctx, jmespath_expr)
    from .jmespath import search as jp_search

    try:
        results = jp_search(path, data)
    except Exception as e:
        raise ContextLoadError(f"failed to apply JMESPath {path}: {e}") from e
    ctx.add_json({entry.name: results})


def _fetch_api_data(entry, policy_ctx: PolicyContext):
    """jsonContext.go:130 fetchAPIData."""
    url_path = (entry.api_call or {}).get("urlPath", "")
    path_str = substitute_all(policy_ctx.json_context, url_path)
    p = parse_api_path(path_str)

    if policy_ctx.client is None:
        raise ContextLoadError("API client is not available")
    if p.name:
        r = policy_ctx.client.get_resource(
            p.api_version, p.resource_type, p.namespace, p.name
        )
        if r is None:
            raise ContextLoadError(f"failed to get resource with urlPath {p}")
        return r
    items = policy_ctx.client.list_resource(p.api_version, p.resource_type, p.namespace)
    return {"items": list(items or []), "kind": "List"}


def parse_multiline_block_body(m: dict) -> dict:
    """jsonContext.go:248 parseMultilineBlockBody: string values containing
    newlines split into lists, except PEM blocks; single-line strings get
    trailing newlines trimmed."""
    out = {}
    for k, v in m.items():
        if isinstance(v, str):
            trimmed = v.strip("\n")
            if "-----BEGIN" not in trimmed and "\n" in trimmed:
                out[k] = trimmed.split("\n")
            else:
                out[k] = trimmed
        else:
            out[k] = v
    return out
