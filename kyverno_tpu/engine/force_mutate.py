"""ForceMutate: apply mutate rules unconditionally (CLI dry-runs).

Mirrors /root/reference/pkg/engine/forceMutate.go:56. Unresolvable
variables become placeholders when no context is given; anchors still
resolve against the resource (a condition miss yields an empty patch).
"""

from __future__ import annotations

import copy

from .mutate.handlers import (
    process_patches_json6902,
    process_raw_patches,
    process_strategic_merge,
)
from .response import RuleStatus
from .variables import substitute_all_force_mutate


class ForceMutateError(Exception):
    pass


def force_mutate(ctx, policy, resource: dict) -> dict:
    """forceMutate.go:56 ForceMutate: returns the fully mutated resource."""
    resource = copy.deepcopy(resource)
    for rule in policy.spec.rules:
        if not rule.has_mutate():
            continue

        mutation = copy.copy(rule.mutation)
        if mutation.overlay is not None:
            mutation.overlay = substitute_all_force_mutate(ctx, mutation.overlay)
        if mutation.patch_strategic_merge is not None:
            mutation.patch_strategic_merge = substitute_all_force_mutate(
                ctx, mutation.patch_strategic_merge
            )
        if mutation.patches:
            mutation.patches = substitute_all_force_mutate(ctx, mutation.patches)
        if mutation.patches_json6902:
            mutation.patches_json6902 = substitute_all_force_mutate(
                ctx, mutation.patches_json6902
            )

        if mutation.overlay is not None:
            result = process_strategic_merge(mutation.overlay, resource)
            if result.status is not RuleStatus.PASS:
                raise ForceMutateError(
                    f"failed to mutate resource with overlay rule {rule.name}: {result.message}"
                )
            resource = result.patched_resource

        if mutation.patches:
            result = process_raw_patches(mutation.patches, resource)
            if result.status is not RuleStatus.PASS:
                raise ForceMutateError(result.message)
            resource = result.patched_resource

        if mutation.patch_strategic_merge is not None:
            result = process_strategic_merge(mutation.patch_strategic_merge, resource)
            if result.status is not RuleStatus.PASS:
                raise ForceMutateError(result.message)
            resource = result.patched_resource

        if mutation.patches_json6902:
            result = process_patches_json6902(mutation.patches_json6902, resource)
            if result.status is not RuleStatus.PASS:
                raise ForceMutateError(result.message)
            resource = result.patched_resource

        if mutation.foreach:
            for fe in mutation.foreach:
                if fe.patch_strategic_merge is not None:
                    psm = substitute_all_force_mutate(ctx, fe.patch_strategic_merge)
                    result = process_strategic_merge(psm, resource)
                    if result.status is not RuleStatus.PASS:
                        raise ForceMutateError(result.message)
                    resource = result.patched_resource
    return resource
