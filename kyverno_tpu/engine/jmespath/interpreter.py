"""JMESPath tree-walking evaluator."""

from __future__ import annotations

from .errors import JMESPathError, NotFoundError
from .functions import FUNCTIONS, Expref
from .parser import compile as compile_expr


def is_false(value) -> bool:
    """JMESPath truthiness: null, empty string/array/object, and False are
    false-like."""
    return (
        value is None
        or value is False
        or (isinstance(value, (str, list, dict)) and len(value) == 0)
    )


def search(expression: str, data):
    return evaluate(compile_expr(expression), data)


def evaluate(node, value):
    tag = node[0]
    return _HANDLERS[tag](node, value)


def _soft(node, value):
    """Evaluate treating the fork's missing-key NotFoundError as null.

    The hard error is only wanted on the *spine* of a path expression (so
    unresolved {{variables}} are detected); inside projections, filters,
    logical operators, comparators, and function arguments a missing key
    behaves like standard-JMESPath null."""
    try:
        return evaluate(node, value)
    except NotFoundError:
        return None


def _identity(node, value):
    return value


def _current(node, value):
    return value


def _literal(node, value):
    return node[1]


def _field(node, value):
    # The reference pins the kyverno/go-jmespath fork (go.mod:64), which
    # turns a missing map key into a NotFoundError instead of null — the
    # variable system depends on this to detect unresolved variables.
    if isinstance(value, dict):
        if node[1] not in value:
            raise NotFoundError(f'Unknown key "{node[1]}" in path')
        return value[node[1]]
    return None


def _subexpression(node, value):
    left = evaluate(node[1], value)
    if left is None:
        return None
    return evaluate(node[2], left)


def _index_expression(node, value):
    left = evaluate(node[1], value)
    return evaluate(node[2], left)


def _index(node, value):
    if not isinstance(value, list):
        return None
    i = node[1]
    if -len(value) <= i < len(value):
        return value[i]
    return None


def _slice(node, value):
    if not isinstance(value, list):
        return None
    start, stop, step = node[1], node[2], node[3]
    if step == 0:
        raise JMESPathError("slice step cannot be 0")
    return value[slice(start, stop, step)]


def _projection(node, value):
    base = evaluate(node[1], value)
    if not isinstance(base, list):
        return None
    out = []
    for el in base:
        r = _soft(node[2], el)
        if r is not None:
            out.append(r)
    return out


def _value_projection(node, value):
    base = evaluate(node[1], value)
    if not isinstance(base, dict):
        return None
    out = []
    for el in base.values():
        r = _soft(node[2], el)
        if r is not None:
            out.append(r)
    return out


def _flatten_projection(node, value):
    base = evaluate(node[1], value)
    if not isinstance(base, list):
        return None
    merged = []
    for el in base:
        if isinstance(el, list):
            merged.extend(el)
        else:
            merged.append(el)
    right = node[2] or ("identity",)
    out = []
    for el in merged:
        r = _soft(right, el)
        if r is not None:
            out.append(r)
    return out


def _filter_projection(node, value):
    base = evaluate(node[1], value)
    if not isinstance(base, list):
        return None
    cond = node[3]
    right = node[2] or ("identity",)
    out = []
    for el in base:
        if not is_false(_soft(cond, el)):
            r = _soft(right, el)
            if r is not None:
                out.append(r)
    return out


def _comparator(node, value):
    op = node[1]
    left = _soft(node[2], value)
    right = _soft(node[3], value)
    if op == "==":
        return _deep_eq(left, right)
    if op == "!=":
        return not _deep_eq(left, right)
    if not _is_number(left) or not _is_number(right):
        return None  # ordering comparators only apply to numbers
    if op == "<":
        return left < right
    if op == "<=":
        return left <= right
    if op == ">":
        return left > right
    if op == ">=":
        return left >= right
    raise JMESPathError(f"unknown comparator {op}")


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _deep_eq(a, b) -> bool:
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b if isinstance(a, bool) and isinstance(b, bool) else False
    if _is_number(a) and _is_number(b):
        return a == b
    if type(a) is not type(b):
        return False
    return a == b


def _or(node, value):
    left = _soft(node[1], value)
    if is_false(left):
        return _soft(node[2], value)
    return left


def _and(node, value):
    left = _soft(node[1], value)
    if is_false(left):
        return left
    return _soft(node[2], value)


def _not(node, value):
    return is_false(_soft(node[1], value))


def _pipe(node, value):
    return evaluate(node[2], evaluate(node[1], value))


def _multiselect_list(node, value):
    if value is None:
        return None
    return [_soft(e, value) for e in node[1]]


def _multiselect_dict(node, value):
    if value is None:
        return None
    return {k: _soft(e, value) for k, e in node[1]}


def _function(node, value):
    name = node[1]
    fn = FUNCTIONS.get(name)
    if fn is None:
        raise JMESPathError(f"unknown function: {name}()")
    args = [_soft(a, value) for a in node[2]]
    return fn(args)


def _expref(node, value):
    return Expref(node[1], _soft)


_HANDLERS = {
    "identity": _identity,
    "current": _current,
    "literal": _literal,
    "field": _field,
    "subexpression": _subexpression,
    "index_expression": _index_expression,
    "index": _index,
    "slice": _slice,
    "projection": _projection,
    "value_projection": _value_projection,
    "flatten_projection": _flatten_projection,
    "filter_projection": _filter_projection,
    "comparator": _comparator,
    "or": _or,
    "and": _and,
    "not": _not,
    "pipe": _pipe,
    "multiselect_list": _multiselect_list,
    "multiselect_dict": _multiselect_dict,
    "function": _function,
    "expref": _expref,
}
