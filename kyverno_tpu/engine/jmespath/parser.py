"""JMESPath Pratt parser producing tuple-AST nodes.

Node shapes (tag, *payload):
  ("field", name) ("index", i) ("slice", a, b, c) ("identity",)
  ("literal", v) ("subexpression", l, r) ("index_expression", l, r)
  ("projection", l, r) ("value_projection", l, r)
  ("flatten_projection", l, r) ("filter_projection", l, r, cond)
  ("comparator", op, l, r) ("or", l, r) ("and", l, r) ("not", e)
  ("pipe", l, r) ("multiselect_list", [e...]) ("multiselect_dict", [(k,e)...])
  ("function", name, [args]) ("expref", e) ("current",)
"""

from __future__ import annotations

from .errors import ParseError
from .lexer import Token, tokenize

BINDING_POWER = {
    "eof": 0,
    "unquoted_identifier": 0,
    "quoted_identifier": 0,
    "literal": 0,
    "rbracket": 0,
    "rparen": 0,
    "comma": 0,
    "rbrace": 0,
    "number": 0,
    "current": 0,
    "expref": 0,
    "colon": 0,
    "pipe": 1,
    "or": 2,
    "and": 3,
    "eq": 5,
    "gt": 5,
    "lt": 5,
    "gte": 5,
    "lte": 5,
    "ne": 5,
    "flatten": 9,
    "star": 20,
    "filter": 21,
    "dot": 40,
    "not": 45,
    "lbrace": 50,
    "lbracket": 55,
    "lparen": 60,
}

COMPARATORS = {"eq": "==", "ne": "!=", "lt": "<", "gt": ">", "lte": "<=", "gte": ">="}

_PROJECTION_STOP = 10


class Parser:
    def __init__(self, expression: str):
        self.expression = expression
        self.tokens = tokenize(expression)
        self.pos = 0

    # ------------------------------------------------------------- helpers

    @property
    def current(self) -> Token:
        return self.tokens[self.pos]

    def advance(self) -> Token:
        t = self.tokens[self.pos]
        self.pos += 1
        return t

    def expect(self, ttype: str) -> Token:
        t = self.current
        if t.type != ttype:
            raise ParseError(
                f"expected {ttype} but got {t.type} at {t.start} in {self.expression!r}"
            )
        return self.advance()

    # --------------------------------------------------------------- pratt

    def parse(self):
        result = self.expression_rule(0)
        if self.current.type != "eof":
            t = self.current
            raise ParseError(f"unexpected token {t.type} at {t.start} in {self.expression!r}")
        return result

    def expression_rule(self, rbp: int):
        left = self.nud(self.advance())
        while rbp < BINDING_POWER[self.current.type]:
            left = self.led(self.advance(), left)
        return left

    # ---------------------------------------------------------------- nud

    def nud(self, token: Token):
        tt = token.type
        if tt == "unquoted_identifier":
            if self.current.type == "lparen":
                return self._parse_function(token.value)
            return ("field", token.value)
        if tt == "quoted_identifier":
            if self.current.type == "lparen":
                raise ParseError("quoted identifiers cannot be function names")
            return ("field", token.value)
        if tt == "literal":
            return ("literal", token.value)
        if tt == "star":
            return self._parse_value_projection(("identity",))
        if tt == "current":
            return ("current",)
        if tt == "expref":
            return ("expref", self.expression_rule(BINDING_POWER["expref"]))
        if tt == "not":
            return ("not", self.expression_rule(BINDING_POWER["not"]))
        if tt == "lparen":
            inner = self.expression_rule(0)
            self.expect("rparen")
            return inner
        if tt == "flatten":
            return self._parse_projection_rhs(("flatten_projection", ("identity",), None), BINDING_POWER["flatten"])
        if tt == "lbracket":
            return self._parse_bracket_nud()
        if tt == "filter":
            return self._parse_filter(("identity",))
        if tt == "lbrace":
            return self._parse_multiselect_dict()
        raise ParseError(f"unexpected token {tt} ({token.value!r}) at {token.start}")

    def _parse_bracket_nud(self):
        # "[" already consumed: [*] / [i] / [a:b] / [e1,e2]
        if self.current.type == "star" and self.tokens[self.pos + 1].type == "rbracket":
            self.advance()
            self.advance()
            return self._parse_projection_rhs(("projection", ("identity",), None), BINDING_POWER["star"])
        if self.current.type in ("number", "colon"):
            node = self._parse_index_or_slice()
            if node[0] == "slice":
                return self._parse_projection_rhs(
                    ("projection", ("index_expression", ("identity",), node), None),
                    BINDING_POWER["star"],
                )
            return ("index_expression", ("identity",), node)
        return self._parse_multiselect_list()

    # ---------------------------------------------------------------- led

    def led(self, token: Token, left):
        tt = token.type
        if tt == "dot":
            if self.current.type == "star":
                self.advance()
                return self._parse_value_projection(left)
            right = self._parse_dot_rhs(BINDING_POWER["dot"])
            return ("subexpression", left, right)
        if tt == "pipe":
            return ("pipe", left, self.expression_rule(BINDING_POWER["pipe"]))
        if tt == "or":
            return ("or", left, self.expression_rule(BINDING_POWER["or"]))
        if tt == "and":
            return ("and", left, self.expression_rule(BINDING_POWER["and"]))
        if tt in COMPARATORS:
            return ("comparator", COMPARATORS[tt], left, self.expression_rule(BINDING_POWER[tt]))
        if tt == "flatten":
            return self._parse_projection_rhs(("flatten_projection", left, None), BINDING_POWER["flatten"])
        if tt == "filter":
            return self._parse_filter(left)
        if tt == "lbracket":
            if self.current.type in ("number", "colon"):
                node = self._parse_index_or_slice()
                if node[0] == "slice":
                    return self._parse_projection_rhs(
                        ("projection", ("index_expression", left, node), None),
                        BINDING_POWER["star"],
                    )
                return ("index_expression", left, node)
            if self.current.type == "star" and self.tokens[self.pos + 1].type == "rbracket":
                self.advance()
                self.advance()
                return self._parse_projection_rhs(("projection", left, None), BINDING_POWER["star"])
            raise ParseError(f"unexpected token in brackets at {token.start}")
        raise ParseError(f"unexpected led token {tt} at {token.start}")

    # ------------------------------------------------------------ snippets

    def _parse_index_or_slice(self):
        parts = [None, None, None]
        idx = 0
        saw_colon = False
        if self.current.type == "number":
            parts[0] = self.advance().value
        while self.current.type == "colon":
            saw_colon = True
            idx += 1
            if idx > 2:
                raise ParseError("too many colons in slice")
            self.advance()
            if self.current.type == "number":
                parts[idx] = self.advance().value
        self.expect("rbracket")
        if not saw_colon:
            return ("index", parts[0])
        return ("slice", parts[0], parts[1], parts[2])

    def _parse_projection_rhs(self, projection, rbp: int):
        """RHS binds at the projection's own power so that chained dots and
        brackets fold INTO the projection, stopping only at pipe/or/and/
        comparators."""
        tag = projection[0]
        left = projection[1]
        cond = projection[3] if tag == "filter_projection" else None
        if BINDING_POWER[self.current.type] < _PROJECTION_STOP:
            right = ("identity",)
        elif self.current.type == "dot":
            self.advance()
            right = self._parse_dot_rhs(rbp)
        elif self.current.type in ("lbracket", "filter", "flatten"):
            right = self.expression_rule(rbp)
        else:
            t = self.current
            raise ParseError(f"unexpected token {t.type} after projection at {t.start}")
        if tag == "filter_projection":
            return (tag, left, right, cond)
        return (tag, left, right)

    def _parse_value_projection(self, left):
        rbp = BINDING_POWER["star"]
        if BINDING_POWER[self.current.type] < _PROJECTION_STOP:
            right = ("identity",)
        elif self.current.type == "dot":
            self.advance()
            right = self._parse_dot_rhs(rbp)
        elif self.current.type in ("lbracket", "filter", "flatten"):
            right = self.expression_rule(rbp)
        else:
            t = self.current
            raise ParseError(f"unexpected token {t.type} after '*' at {t.start}")
        return ("value_projection", left, right)

    def _parse_dot_rhs(self, rbp: int):
        tt = self.current.type
        if tt in ("unquoted_identifier", "quoted_identifier", "star"):
            return self.expression_rule(rbp)
        if tt == "lbracket":
            self.advance()
            return self._parse_multiselect_list()
        if tt == "lbrace":
            self.advance()
            return self._parse_multiselect_dict()
        raise ParseError(f"unexpected token {tt} after '.' at {self.current.start}")

    def _parse_multiselect_list(self):
        nodes = []
        while True:
            nodes.append(self.expression_rule(0))
            if self.current.type == "rbracket":
                break
            self.expect("comma")
        self.expect("rbracket")
        return ("multiselect_list", nodes)

    def _parse_multiselect_dict(self):
        pairs = []
        while True:
            key_token = self.current
            if key_token.type not in ("unquoted_identifier", "quoted_identifier"):
                raise ParseError(f"expected identifier key at {key_token.start}")
            self.advance()
            self.expect("colon")
            pairs.append((key_token.value, self.expression_rule(0)))
            if self.current.type == "rbrace":
                break
            self.expect("comma")
        self.expect("rbrace")
        return ("multiselect_dict", pairs)

    def _parse_filter(self, left):
        cond = self.expression_rule(0)
        self.expect("rbracket")
        return self._parse_projection_rhs(("filter_projection", left, None, cond), BINDING_POWER["filter"])

    def _parse_function(self, name: str):
        self.expect("lparen")
        args = []
        if self.current.type != "rparen":
            while True:
                args.append(self.expression_rule(0))
                if self.current.type == "rparen":
                    break
                self.expect("comma")
        self.expect("rparen")
        return ("function", name, args)


_cache: dict[str, tuple] = {}


def compile(expression: str):
    """Parse with memoization (expressions repeat heavily across policies)."""
    ast = _cache.get(expression)
    if ast is None:
        ast = Parser(expression).parse()
        if len(_cache) > 4096:
            _cache.clear()
        _cache[expression] = ast
    return ast
