"""JMESPath tokenizer (spec-conformant)."""

from __future__ import annotations

import json
import string
from dataclasses import dataclass

from .errors import LexerError

IDENT_START = set(string.ascii_letters + "_")
IDENT_CHARS = set(string.ascii_letters + string.digits + "_")
NUMBER_CHARS = set(string.digits)

SIMPLE_TOKENS = {
    ".": "dot",
    "*": "star",
    "]": "rbracket",
    ",": "comma",
    ":": "colon",
    "@": "current",
    "(": "lparen",
    ")": "rparen",
    "{": "lbrace",
    "}": "rbrace",
}


@dataclass
class Token:
    type: str
    value: object
    start: int


def tokenize(expression: str) -> list[Token]:
    tokens: list[Token] = []
    pos = 0
    n = len(expression)
    while pos < n:
        ch = expression[pos]
        if ch in SIMPLE_TOKENS:
            tokens.append(Token(SIMPLE_TOKENS[ch], ch, pos))
            pos += 1
        elif ch in " \t\n\r":
            pos += 1
        elif ch in IDENT_START:
            start = pos
            while pos < n and expression[pos] in IDENT_CHARS:
                pos += 1
            tokens.append(Token("unquoted_identifier", expression[start:pos], start))
        elif ch == "[":
            if pos + 1 < n and expression[pos + 1] == "]":
                tokens.append(Token("flatten", "[]", pos))
                pos += 2
            elif pos + 1 < n and expression[pos + 1] == "?":
                tokens.append(Token("filter", "[?", pos))
                pos += 2
            else:
                tokens.append(Token("lbracket", "[", pos))
                pos += 1
        elif ch == "'":
            start = pos
            pos += 1
            chunks = []
            while pos < n and expression[pos] != "'":
                if expression[pos] == "\\" and pos + 1 < n and expression[pos + 1] in "\\'":
                    chunks.append(expression[pos + 1])
                    pos += 2
                else:
                    chunks.append(expression[pos])
                    pos += 1
            if pos >= n:
                raise LexerError(f"unterminated raw string at {start}")
            pos += 1
            tokens.append(Token("literal", "".join(chunks), start))
        elif ch == '"':
            start = pos
            pos += 1
            while pos < n and expression[pos] != '"':
                if expression[pos] == "\\":
                    pos += 2
                else:
                    pos += 1
            if pos >= n:
                raise LexerError(f"unterminated quoted identifier at {start}")
            pos += 1
            raw = expression[start:pos]
            try:
                value = json.loads(raw)
            except ValueError as e:
                raise LexerError(f"invalid quoted identifier {raw!r}: {e}")
            tokens.append(Token("quoted_identifier", value, start))
        elif ch == "`":
            start = pos
            pos += 1
            chunks = []
            while pos < n and expression[pos] != "`":
                if expression[pos] == "\\" and pos + 1 < n and expression[pos + 1] == "`":
                    chunks.append("`")
                    pos += 2
                else:
                    chunks.append(expression[pos])
                    pos += 1
            if pos >= n:
                raise LexerError(f"unterminated literal at {start}")
            pos += 1
            raw = "".join(chunks)
            try:
                value = json.loads(raw)
            except ValueError:
                # the spec allows bare strings inside backticks
                value = raw.strip()
            tokens.append(Token("literal", value, start))
        elif ch == "-" or ch in NUMBER_CHARS:
            start = pos
            pos += 1
            while pos < n and expression[pos] in NUMBER_CHARS:
                pos += 1
            text = expression[start:pos]
            if text == "-":
                raise LexerError(f"unexpected '-' at position {start}")
            tokens.append(Token("number", int(text), start))
        elif ch == "|":
            if pos + 1 < n and expression[pos + 1] == "|":
                tokens.append(Token("or", "||", pos))
                pos += 2
            else:
                tokens.append(Token("pipe", "|", pos))
                pos += 1
        elif ch == "&":
            if pos + 1 < n and expression[pos + 1] == "&":
                tokens.append(Token("and", "&&", pos))
                pos += 2
            else:
                tokens.append(Token("expref", "&", pos))
                pos += 1
        elif ch == "=":
            if pos + 1 < n and expression[pos + 1] == "=":
                tokens.append(Token("eq", "==", pos))
                pos += 2
            else:
                raise LexerError(f"unexpected '=' at {pos}")
        elif ch == "!":
            if pos + 1 < n and expression[pos + 1] == "=":
                tokens.append(Token("ne", "!=", pos))
                pos += 2
            else:
                tokens.append(Token("not", "!", pos))
                pos += 1
        elif ch == "<":
            if pos + 1 < n and expression[pos + 1] == "=":
                tokens.append(Token("lte", "<=", pos))
                pos += 2
            else:
                tokens.append(Token("lt", "<", pos))
                pos += 1
        elif ch == ">":
            if pos + 1 < n and expression[pos + 1] == "=":
                tokens.append(Token("gte", ">=", pos))
                pos += 2
            else:
                tokens.append(Token("gt", ">", pos))
                pos += 1
        else:
            raise LexerError(f"unknown character {ch!r} at position {pos}")
    tokens.append(Token("eof", "", n))
    return tokens
