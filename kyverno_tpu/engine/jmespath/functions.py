"""JMESPath builtin functions + the kyverno dialect extensions.

Builtins follow the JMESPath spec. Extensions mirror
/root/reference/pkg/engine/jmespath/functions.go (19 functions).
"""

from __future__ import annotations

import base64
import json
import math
import re

from .errors import FunctionError


class Expref:
    """An &expression argument (passed to sort_by/max_by/map/...)."""

    def __init__(self, node, evaluate):
        self.node = node
        self._evaluate = evaluate

    def __call__(self, value):
        return self._evaluate(self.node, value)


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _typeof(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if _is_number(v):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, list):
        return "array"
    if isinstance(v, dict):
        return "object"
    if isinstance(v, Expref):
        return "expref"
    raise FunctionError(f"unknown type: {type(v)}")


def _require(args, n, name):
    if len(args) != n:
        raise FunctionError(f"{name}() takes {n} arguments, got {len(args)}")


def _require_type(v, types, name, argn):
    if _typeof(v) not in types:
        raise FunctionError(
            f"{name}() argument {argn} must be {'/'.join(types)}, got {_typeof(v)}"
        )
    return v


def _as_str(v, name, argn):
    """Kyverno's regex helpers accept strings or numbers (functions.go)."""
    if isinstance(v, str):
        return v
    if _is_number(v):
        if isinstance(v, float) and v == math.trunc(v):
            return str(int(v))
        return str(v)
    raise FunctionError(f"{name}() argument {argn} must be string or number")


# ------------------------------------------------------------------ builtins


def _fn_abs(args):
    _require(args, 1, "abs")
    _require_type(args[0], ["number"], "abs", 1)
    return abs(args[0])


def _fn_avg(args):
    _require(args, 1, "avg")
    arr = _require_type(args[0], ["array"], "avg", 1)
    if not arr:
        return None
    for v in arr:
        if not _is_number(v):
            raise FunctionError("avg() requires an array of numbers")
    return sum(arr) / len(arr)


def _fn_ceil(args):
    _require(args, 1, "ceil")
    _require_type(args[0], ["number"], "ceil", 1)
    return math.ceil(args[0])


def _fn_contains(args):
    _require(args, 2, "contains")
    subject = _require_type(args[0], ["array", "string"], "contains", 1)
    if isinstance(subject, str):
        if not isinstance(args[1], str):
            return False
        return args[1] in subject
    return args[1] in subject


def _fn_ends_with(args):
    _require(args, 2, "ends_with")
    s = _require_type(args[0], ["string"], "ends_with", 1)
    suffix = _require_type(args[1], ["string"], "ends_with", 2)
    return s.endswith(suffix)


def _fn_floor(args):
    _require(args, 1, "floor")
    _require_type(args[0], ["number"], "floor", 1)
    return math.floor(args[0])


def _fn_join(args):
    _require(args, 2, "join")
    sep = _require_type(args[0], ["string"], "join", 1)
    arr = _require_type(args[1], ["array"], "join", 2)
    for v in arr:
        if not isinstance(v, str):
            raise FunctionError("join() requires an array of strings")
    return sep.join(arr)


def _fn_keys(args):
    _require(args, 1, "keys")
    obj = _require_type(args[0], ["object"], "keys", 1)
    return list(obj.keys())


def _fn_length(args):
    _require(args, 1, "length")
    v = _require_type(args[0], ["string", "array", "object"], "length", 1)
    return len(v)


def _fn_map(args):
    _require(args, 2, "map")
    expref = _require_type(args[0], ["expref"], "map", 1)
    arr = _require_type(args[1], ["array"], "map", 2)
    return [expref(v) for v in arr]


def _fn_max(args):
    _require(args, 1, "max")
    arr = _require_type(args[0], ["array"], "max", 1)
    if not arr:
        return None
    if all(_is_number(v) for v in arr) or all(isinstance(v, str) for v in arr):
        return max(arr)
    raise FunctionError("max() requires a homogeneous array of numbers or strings")


def _fn_max_by(args):
    _require(args, 2, "max_by")
    arr = _require_type(args[0], ["array"], "max_by", 1)
    expref = _require_type(args[1], ["expref"], "max_by", 2)
    if not arr:
        return None
    keyed = [(expref(v), v) for v in arr]
    _check_by_keys(keyed, "max_by")
    return max(keyed, key=lambda kv: kv[0])[1]


def _fn_merge(args):
    if not args:
        raise FunctionError("merge() requires at least one argument")
    out = {}
    for a in args:
        _require_type(a, ["object"], "merge", 1)
        out.update(a)
    return out


def _fn_min(args):
    _require(args, 1, "min")
    arr = _require_type(args[0], ["array"], "min", 1)
    if not arr:
        return None
    if all(_is_number(v) for v in arr) or all(isinstance(v, str) for v in arr):
        return min(arr)
    raise FunctionError("min() requires a homogeneous array of numbers or strings")


def _fn_min_by(args):
    _require(args, 2, "min_by")
    arr = _require_type(args[0], ["array"], "min_by", 1)
    expref = _require_type(args[1], ["expref"], "min_by", 2)
    if not arr:
        return None
    keyed = [(expref(v), v) for v in arr]
    _check_by_keys(keyed, "min_by")
    return min(keyed, key=lambda kv: kv[0])[1]


def _check_by_keys(keyed, name):
    keys = [k for k, _ in keyed]
    if not (all(_is_number(k) for k in keys) or all(isinstance(k, str) for k in keys)):
        raise FunctionError(f"{name}() expression must produce numbers or strings")


def _fn_not_null(args):
    if not args:
        raise FunctionError("not_null() requires at least one argument")
    for a in args:
        if a is not None:
            return a
    return None


def _fn_reverse(args):
    _require(args, 1, "reverse")
    v = _require_type(args[0], ["array", "string"], "reverse", 1)
    if isinstance(v, str):
        return v[::-1]
    return list(reversed(v))


def _fn_sort(args):
    _require(args, 1, "sort")
    arr = _require_type(args[0], ["array"], "sort", 1)
    if not arr:
        return []
    if all(_is_number(v) for v in arr) or all(isinstance(v, str) for v in arr):
        return sorted(arr)
    raise FunctionError("sort() requires a homogeneous array of numbers or strings")


def _fn_sort_by(args):
    _require(args, 2, "sort_by")
    arr = _require_type(args[0], ["array"], "sort_by", 1)
    expref = _require_type(args[1], ["expref"], "sort_by", 2)
    if not arr:
        return []
    keyed = [(expref(v), v) for v in arr]
    _check_by_keys(keyed, "sort_by")
    return [v for _, v in sorted(keyed, key=lambda kv: kv[0])]


def _fn_starts_with(args):
    _require(args, 2, "starts_with")
    s = _require_type(args[0], ["string"], "starts_with", 1)
    prefix = _require_type(args[1], ["string"], "starts_with", 2)
    return s.startswith(prefix)


def _fn_sum(args):
    _require(args, 1, "sum")
    arr = _require_type(args[0], ["array"], "sum", 1)
    for v in arr:
        if not _is_number(v):
            raise FunctionError("sum() requires an array of numbers")
    return sum(arr)


def _fn_to_array(args):
    _require(args, 1, "to_array")
    if isinstance(args[0], list):
        return args[0]
    return [args[0]]  # spec: any non-array (incl. null) wraps to [value]


def _fn_to_number(args):
    _require(args, 1, "to_number")
    v = args[0]
    if _is_number(v):
        return v
    if isinstance(v, str):
        try:
            f = float(v)
            return int(f) if f == math.trunc(f) and ("e" not in v.lower() and "." not in v) else f
        except ValueError:
            return None
    return None


def _fn_to_string(args):
    _require(args, 1, "to_string")
    if isinstance(args[0], str):
        return args[0]
    return json.dumps(args[0], separators=(",", ":"))


def _fn_type(args):
    _require(args, 1, "type")
    return _typeof(args[0])


def _fn_values(args):
    _require(args, 1, "values")
    obj = _require_type(args[0], ["object"], "values", 1)
    return list(obj.values())


# ---------------------------------------------------------- kyverno dialect


def _kf_compare(args):
    _require(args, 2, "compare")
    a = _require_type(args[0], ["string"], "compare", 1)
    b = _require_type(args[1], ["string"], "compare", 2)
    return -1 if a < b else (1 if a > b else 0)


def _kf_equal_fold(args):
    _require(args, 2, "equal_fold")
    a = _require_type(args[0], ["string"], "equal_fold", 1)
    b = _require_type(args[1], ["string"], "equal_fold", 2)
    return a.casefold() == b.casefold()


def _kf_replace(args):
    _require(args, 4, "replace")
    s = _require_type(args[0], ["string"], "replace", 1)
    old = _require_type(args[1], ["string"], "replace", 2)
    new = _require_type(args[2], ["string"], "replace", 3)
    n = _require_type(args[3], ["number"], "replace", 4)
    n = int(n)
    if n < 0:
        return s.replace(old, new)
    return s.replace(old, new, n)


def _kf_replace_all(args):
    _require(args, 3, "replace_all")
    s = _require_type(args[0], ["string"], "replace_all", 1)
    old = _require_type(args[1], ["string"], "replace_all", 2)
    new = _require_type(args[2], ["string"], "replace_all", 3)
    return s.replace(old, new)


def _kf_to_upper(args):
    _require(args, 1, "to_upper")
    return _require_type(args[0], ["string"], "to_upper", 1).upper()


def _kf_to_lower(args):
    _require(args, 1, "to_lower")
    return _require_type(args[0], ["string"], "to_lower", 1).lower()


def _kf_trim(args):
    _require(args, 2, "trim")
    s = _require_type(args[0], ["string"], "trim", 1)
    cutset = _require_type(args[1], ["string"], "trim", 2)
    return s.strip(cutset)  # Go strings.Trim semantics: cutset of chars


def _kf_split(args):
    _require(args, 2, "split")
    s = _require_type(args[0], ["string"], "split", 1)
    sep = _require_type(args[1], ["string"], "split", 2)
    if sep == "":
        return list(s)
    return s.split(sep)


def _go_expand_repl(compiled: re.Pattern, repl: str):
    """Build a replacement callable with Go Regexp.ReplaceAllString
    semantics: $N / $name / ${name} expand to the matched group, and
    references to groups that don't exist expand to the empty string
    (Python's re raises instead)."""

    def expand(m: re.Match) -> str:
        out = []
        i, n = 0, len(repl)
        while i < n:
            c = repl[i]
            if c != "$":
                out.append(c)
                i += 1
                continue
            if i + 1 < n and repl[i + 1] == "$":
                out.append("$")
                i += 2
                continue
            j = i + 1
            braced = j < n and repl[j] == "{"
            if braced:
                j += 1
            start = j
            while j < n and (repl[j].isalnum() or repl[j] == "_"):
                j += 1
            name = repl[start:j]
            if braced:
                if j < n and repl[j] == "}":
                    j += 1
                else:  # unterminated ${ — Go emits nothing
                    i = j
                    continue
            if not name:
                out.append("$")
                i += 1
                continue
            if name.isdigit():
                idx = int(name)
                out.append((m.group(idx) or "") if idx <= compiled.groups else "")
            else:
                out.append((m.group(name) or "") if name in compiled.groupindex else "")
            i = j
        return "".join(out)

    return expand


def _kf_regex_replace_all(args):
    _require(args, 3, "regex_replace_all")
    pattern = _require_type(args[0], ["string"], "regex_replace_all", 1)
    src = _as_str(args[1], "regex_replace_all", 2)
    repl = _as_str(args[2], "regex_replace_all", 3)
    try:
        compiled = re.compile(pattern)
        return compiled.sub(_go_expand_repl(compiled, repl), src)
    except re.error as e:
        raise FunctionError(f"regex_replace_all(): {e}")


def _kf_regex_replace_all_literal(args):
    _require(args, 3, "regex_replace_all_literal")
    pattern = _require_type(args[0], ["string"], "regex_replace_all_literal", 1)
    src = _as_str(args[1], "regex_replace_all_literal", 2)
    repl = _as_str(args[2], "regex_replace_all_literal", 3)
    try:
        return re.sub(pattern, lambda m: repl, src)
    except re.error as e:
        raise FunctionError(f"regex_replace_all_literal(): {e}")


def _kf_regex_match(args):
    _require(args, 2, "regex_match")
    pattern = _require_type(args[0], ["string"], "regex_match", 1)
    s = _as_str(args[1], "regex_match", 2)
    try:
        return re.search(pattern, s) is not None
    except re.error as e:
        raise FunctionError(f"regex_match(): {e}")


def _kf_label_match(args):
    """True iff every (k, v) of the selector object is present in the labels
    object (functions.go jpLabelMatch)."""
    _require(args, 2, "label_match")
    selector = _require_type(args[0], ["object"], "label_match", 1)
    labels = _require_type(args[1], ["object"], "label_match", 2)
    return all(labels.get(k) == v for k, v in selector.items())


def _numeric_pair(args, name):
    _require(args, 2, name)
    a = _require_type(args[0], ["number"], name, 1)
    b = _require_type(args[1], ["number"], name, 2)
    return a, b


def _kf_add(args):
    a, b = _numeric_pair(args, "add")
    return a + b


def _kf_subtract(args):
    a, b = _numeric_pair(args, "subtract")
    return a - b


def _kf_multiply(args):
    a, b = _numeric_pair(args, "multiply")
    return a * b


def _kf_divide(args):
    a, b = _numeric_pair(args, "divide")
    if b == 0:
        raise FunctionError("divide: division by zero")
    r = a / b
    return r


def _kf_modulo(args):
    a, b = _numeric_pair(args, "modulo")
    ia, ib = int(a), int(b)
    if ia != a or ib != b:
        raise FunctionError("modulo: operands must be integers")
    if ib == 0:
        raise FunctionError("modulo: division by zero")
    return int(math.fmod(ia, ib))  # Go % truncates toward zero


def _kf_base64_decode(args):
    _require(args, 1, "base64_decode")
    s = _require_type(args[0], ["string"], "base64_decode", 1)
    try:
        return base64.b64decode(s).decode("utf-8")
    except Exception as e:
        raise FunctionError(f"base64_decode(): {e}")


def _kf_base64_encode(args):
    _require(args, 1, "base64_encode")
    s = _require_type(args[0], ["string"], "base64_encode", 1)
    return base64.b64encode(s.encode("utf-8")).decode("ascii")


FUNCTIONS = {
    # spec builtins
    "abs": _fn_abs,
    "avg": _fn_avg,
    "ceil": _fn_ceil,
    "contains": _fn_contains,
    "ends_with": _fn_ends_with,
    "floor": _fn_floor,
    "join": _fn_join,
    "keys": _fn_keys,
    "length": _fn_length,
    "map": _fn_map,
    "max": _fn_max,
    "max_by": _fn_max_by,
    "merge": _fn_merge,
    "min": _fn_min,
    "min_by": _fn_min_by,
    "not_null": _fn_not_null,
    "reverse": _fn_reverse,
    "sort": _fn_sort,
    "sort_by": _fn_sort_by,
    "starts_with": _fn_starts_with,
    "sum": _fn_sum,
    "to_array": _fn_to_array,
    "to_number": _fn_to_number,
    "to_string": _fn_to_string,
    "type": _fn_type,
    "values": _fn_values,
    # kyverno dialect (functions.go:57)
    "compare": _kf_compare,
    "equal_fold": _kf_equal_fold,
    "replace": _kf_replace,
    "replace_all": _kf_replace_all,
    "to_upper": _kf_to_upper,
    "to_lower": _kf_to_lower,
    "trim": _kf_trim,
    "split": _kf_split,
    "regex_replace_all": _kf_regex_replace_all,
    "regex_replace_all_literal": _kf_regex_replace_all_literal,
    "regex_match": _kf_regex_match,
    "label_match": _kf_label_match,
    "add": _kf_add,
    "subtract": _kf_subtract,
    "multiply": _kf_multiply,
    "divide": _kf_divide,
    "modulo": _kf_modulo,
    "base64_decode": _kf_base64_decode,
    "base64_encode": _kf_base64_encode,
}
