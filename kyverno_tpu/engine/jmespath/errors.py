class JMESPathError(ValueError):
    """Base error for parse/eval failures."""


class LexerError(JMESPathError):
    pass


class ParseError(JMESPathError):
    pass


class NotFoundError(JMESPathError):
    """Raised by the engine context when a query returns nothing for a
    required variable (mirrors gojmespath.NotFoundError)."""


class FunctionError(JMESPathError):
    pass
