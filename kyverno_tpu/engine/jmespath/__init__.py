"""JMESPath dialect for the policy engine.

A from-scratch JMESPath implementation (the pip package is not available in
the image) following the public JMESPath spec, extended with the 19 custom
functions registered by the reference dialect
(/root/reference/pkg/engine/jmespath/functions.go:57-215): compare,
equal_fold, replace, replace_all, to_upper, to_lower, trim, split,
regex_replace_all, regex_replace_all_literal, regex_match, label_match,
add, subtract, multiply, divide, modulo, base64_decode, base64_encode.
"""

from .errors import JMESPathError, NotFoundError
from .parser import compile as compile_expr
from .interpreter import search

__all__ = ["search", "compile_expr", "JMESPathError", "NotFoundError"]
