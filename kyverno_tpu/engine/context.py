"""JSON context: the single mutable variable store for rule evaluation.

Mirrors /root/reference/pkg/engine/context/context.go: one JSON document
holding ``request.*``, ``images.*`` and named context entries, merged via
RFC7386 merge-patch (null deletes), with a checkpoint/restore stack for
per-rule rollback, queried through the JMESPath dialect.
"""

from __future__ import annotations

from ..utils.jsoncopy import json_copy
import json
from dataclasses import asdict

from .jmespath import JMESPathError, search
from . import resource as res


class InvalidVariableError(Exception):
    """Raised for structurally invalid queries (empty, bad syntax)."""


def merge_patch(target, patch):
    """RFC7386 JSON merge-patch: dict keys merge recursively, null deletes,
    everything else replaces."""
    if not isinstance(patch, dict):
        return json_copy(patch)
    if not isinstance(target, dict):
        target = {}
    else:
        target = dict(target)
    for k, v in patch.items():
        if v is None:
            target.pop(k, None)
        else:
            target[k] = merge_patch(target.get(k), v)
    return target


SA_PREFIX = "system:serviceaccount:"


class Context:
    """context.go:54. The TPU tier snapshots this into immutable per-lane
    dictionaries at compile time; this mutable form drives the CPU tier."""

    def __init__(self):
        self._data: dict = {}
        self._checkpoints: list[dict] = []
        self.images: dict | None = None

    # ------------------------------------------------------------- merging

    def add_json(self, data: dict) -> None:
        self._data = merge_patch(self._data, data)

    def add_request(self, request: dict) -> None:
        """Admission request document at ``request.*`` (context.go:99)."""
        self.add_json({"request": request})

    def add_resource(self, resource: dict) -> None:
        """Resource at ``request.object`` (context.go:116)."""
        self.add_json({"request": {"object": json_copy(resource)}})

    def add_old_resource(self, resource: dict) -> None:
        self.add_json({"request": {"oldObject": json_copy(resource)}})

    def add_user_info(self, request_info) -> None:
        """RequestInfo at ``request.{roles,clusterRoles,userInfo}``."""
        if hasattr(request_info, "__dataclass_fields__"):
            info = asdict(request_info)
            payload = {
                "roles": info.get("roles") or [],
                "clusterRoles": info.get("cluster_roles") or [],
                "userInfo": {
                    "username": info["admission_user_info"].get("username", ""),
                    "uid": info["admission_user_info"].get("uid", ""),
                    "groups": info["admission_user_info"].get("groups") or [],
                },
            }
        else:
            payload = dict(request_info)
        self.add_json({"request": payload})

    def add_service_account(self, username: str) -> None:
        """serviceAccountName/-Namespace from the SA username
        (context.go:204)."""
        sa = username[len(SA_PREFIX):] if len(username) > len(SA_PREFIX) else ""
        name, namespace = "", ""
        groups = sa.split(":")
        if len(groups) >= 2:
            namespace, name = groups[0], groups[1]
        self.add_json({"serviceAccountName": name})
        self.add_json({"serviceAccountNamespace": namespace})

    def add_namespace(self, namespace: str) -> None:
        self.add_json({"request": {"namespace": namespace}})

    def add_element(self, element, index: int) -> None:
        """foreach iteration variable: element / elementIndex."""
        self.add_json({"element": json_copy(element), "elementIndex": index})

    def add_image_info(self, resource: dict) -> None:
        images = extract_image_info(resource)
        if images is None:
            return
        self.images = images
        self.add_json({"images": images})

    # ------------------------------------------------------------ queries

    def query(self, query: str):
        """context/evaluate.go:15. Missing map keys and malformed queries
        raise InvalidVariableError (fork semantics, see interpreter._field)."""
        query = (query or "").strip()
        if not query:
            raise InvalidVariableError("invalid query (empty)")
        try:
            return search(query, self._data)
        except JMESPathError as e:
            raise InvalidVariableError(f"incorrect query {query!r}: {e}") from e

    def has_changed(self, jmespath_expr: str) -> bool:
        """context/evaluate.go:52. Missing keys raise from query(); a path
        resolving to null (e.g. through a null parent) raises here, as the
        reference treats nil results as 'not found'."""
        obj = self.query(f"request.object.{jmespath_expr}")
        if obj is None:
            raise InvalidVariableError(f"request.object.{jmespath_expr} not found")
        old = self.query(f"request.oldObject.{jmespath_expr}")
        if old is None:
            raise InvalidVariableError(f"request.oldObject.{jmespath_expr} not found")
        return obj != old

    def snapshot(self) -> dict:
        return json_copy(self._data)

    # -------------------------------------------------------- checkpoints

    def checkpoint(self) -> None:
        self._checkpoints.append(json_copy(self._data))

    def restore(self) -> None:
        """Pop to the last checkpoint (context.go:322)."""
        if self._checkpoints:
            self._data = self._checkpoints.pop()

    def reset(self) -> None:
        """Return to the last checkpoint, keeping it (context.go:327)."""
        if self._checkpoints:
            self._data = json_copy(self._checkpoints[-1])


# ----------------------------------------------------------- image parsing


def parse_image(image: str, json_pointer: str = "") -> dict | None:
    """Parse a container image reference into its components
    (imageutils.go:152 newImageInfo + addDefaultDomain)."""
    slash = image.find("/")
    head = image[:slash] if slash != -1 else ""
    if slash == -1 or (
        "." not in head and ":" not in head and head != "localhost" and head.lower() == head
    ):
        image = "docker.io/" + image

    rest = image
    digest = ""
    if "@" in rest:
        rest, digest = rest.split("@", 1)
        if not digest.startswith("sha256:"):
            return None
    registry, _, path = rest.partition("/")
    tag = ""
    last = path.rsplit("/", 1)[-1]
    if ":" in last:
        path, _, tag = path.rpartition(":")
    if not path or not registry:
        return None
    name = path.rsplit("/", 1)[-1]
    if not tag:
        tag = "latest"
    info = {
        "registry": registry,
        "name": name,
        "path": path,
        "tag": tag,
        "jsonPath": json_pointer,
    }
    if digest:
        info["digest"] = digest
    return info


def image_string(info: dict) -> str:
    s = f"{info['registry']}/{info['path']}:{info['tag']}"
    if info.get("digest"):
        s += "@" + info["digest"]
    return s


_POD_SPEC_PATHS = {
    "Pod": ["spec"],
    "CronJob": ["spec", "jobTemplate", "spec", "template", "spec"],
}


def extract_image_info(resource: dict) -> dict | None:
    """images.{initContainers,containers}.{name} -> ImageInfo
    (imageutils.go:72 extractImageInfo)."""
    kind = res.get_kind(resource)
    spec_path = _POD_SPEC_PATHS.get(kind, ["spec", "template", "spec"])
    node = resource
    for seg in spec_path:
        node = node.get(seg) if isinstance(node, dict) else None
        if node is None:
            return None
    pointer_base = "/" + "/".join(spec_path)

    out: dict = {}
    for tag in ("initContainers", "containers"):
        containers = node.get(tag)
        if not isinstance(containers, list):
            continue
        bucket = {}
        for i, ctr in enumerate(containers):
            if not isinstance(ctr, dict):
                continue
            name, image = ctr.get("name"), ctr.get("image")
            if not isinstance(name, str) or not isinstance(image, str):
                continue
            info = parse_image(image, f"{pointer_base}/{tag}/{i}/image")
            if info is not None:
                bucket[name] = info
        if bucket:
            out[tag] = bucket
    if not out:
        return None
    out.setdefault("containers", {})
    return out


def mutate_resource_with_image_info(resource: dict, ctx: Context) -> tuple[dict, list]:
    """Canonicalize image fields (docker.io/ prefix, :latest default) via
    JSON patches (imageutils.go:203). Returns (patched resource, patches)."""
    if ctx.images is None:
        return resource, []
    patches = []
    patched = json_copy(resource)
    for bucket in ("containers", "initContainers"):
        for info in (ctx.images.get(bucket) or {}).values():
            pointer = info.get("jsonPath", "")
            value = image_string(info)
            patches.append({"op": "replace", "path": pointer, "value": value})
            _apply_pointer_replace(patched, pointer, value)
    return patched, patches


def _apply_pointer_replace(doc, pointer: str, value) -> None:
    parts = [p for p in pointer.split("/") if p != ""]
    node = doc
    for p in parts[:-1]:
        if isinstance(node, list):
            node = node[int(p)]
        else:
            node = node.get(p)
        if node is None:
            return
    last = parts[-1]
    if isinstance(node, list):
        node[int(last)] = value
    elif isinstance(node, dict):
        node[last] = value


def context_to_json(ctx: Context) -> str:
    return json.dumps(ctx.snapshot(), separators=(",", ":"))
