"""X.509 certificate-chain verification for keyless cosign signatures.

The reference's keyless path (pkg/cosign/cosign.go:88-89: no ``key``
means ``CertEmail = Subject`` + ``RootCerts = getX509CertPool(Roots)``)
trusts the certificate cosign attached to the signature layer
(``dev.sigstore.cosign/certificate`` / ``.../chain`` annotations): the
chain must verify up to one of the policy-supplied roots, the leaf's
SAN must match the policy subject, and the payload signature must
verify with the leaf's public key.

Built on the ``cryptography`` package (in-image) for ASN.1/X.509 —
hand-rolling certificate parsing would be a correctness hazard; the
bare-public-key path keeps the self-contained ECDSA in utils/ecdsa.py.

Expired certificates fail closed: cosign accepts an expired Fulcio leaf
only when a transparency-log timestamp proves signing time, and no tlog
integration exists here, so validity is checked against the wall clock.
"""

from __future__ import annotations

from datetime import datetime, timezone

CERT_ANNOTATION = "dev.sigstore.cosign/certificate"
CHAIN_ANNOTATION = "dev.sigstore.cosign/chain"


class CertChainError(Exception):
    pass


def load_pem_certs(pem: str):
    """PEM bundle -> [Certificate]; raises CertChainError on garbage."""
    from cryptography import x509

    data = pem.encode() if isinstance(pem, str) else pem
    try:
        certs = x509.load_pem_x509_certificates(data)
    except ValueError as e:
        raise CertChainError(f"invalid PEM certificate data: {e}") from e
    if not certs:
        raise CertChainError("no certificates in PEM data")
    return certs


def _check_validity(cert, now: datetime, what: str) -> None:
    nvb = cert.not_valid_before_utc
    nva = cert.not_valid_after_utc
    if now < nvb or now > nva:
        raise CertChainError(
            f"{what} certificate is outside its validity window "
            f"({nvb.isoformat()} .. {nva.isoformat()})")


def _issued_by(child, issuer) -> bool:
    try:
        child.verify_directly_issued_by(issuer)
        return True
    except Exception:
        return False


def _is_ca(cert) -> bool:
    """True when the certificate may issue others: BasicConstraints
    CA=true (absent -> NOT a CA, RFC 5280) and, when KeyUsage is
    present, keyCertSign. verify_directly_issued_by checks only
    name-chaining + signature — without this gate any end-entity cert
    under a trusted root could mint arbitrary identities."""
    from cryptography import x509

    try:
        bc = cert.extensions.get_extension_for_class(
            x509.BasicConstraints).value
        if not bc.ca:
            return False
    except x509.ExtensionNotFound:
        return False
    try:
        ku = cert.extensions.get_extension_for_class(x509.KeyUsage).value
        if not ku.key_cert_sign:
            return False
    except x509.ExtensionNotFound:
        pass
    return True


def verify_chain(leaf, intermediates, roots, now: datetime | None = None) -> None:
    """Verify ``leaf`` chains to one of ``roots`` through (a subset of)
    ``intermediates`` — name chaining + signature at every link, validity
    at every node (getX509CertPool + cosign's chain build). Raises."""
    if not roots:
        raise CertChainError("no trust roots supplied")
    now = now or datetime.now(timezone.utc)
    _check_validity(leaf, now, "leaf")

    current = leaf
    pool = list(intermediates)
    # leaf may itself BE a trusted root (pinned cert in the trust store)
    if any(current == r for r in roots):
        return
    for _ in range(len(pool) + 1):
        for root in roots:
            if _is_ca(root) and _issued_by(current, root):
                _check_validity(root, now, "root")
                return
        for cand in pool:
            if _is_ca(cand) and _issued_by(current, cand):
                _check_validity(cand, now, "intermediate")
                current = cand
                pool.remove(cand)
                break
        else:
            raise CertChainError(
                "certificate chain does not terminate at a trusted root")
    raise CertChainError(
        "certificate chain does not terminate at a trusted root")


def cert_subjects(cert) -> list[str]:
    """The identities a cosign subject check can match: email SANs and
    URI SANs (Fulcio puts the OIDC identity in one of these). The
    subject common name is a fallback ONLY when the cert carries no SAN
    identities — CAs validate SANs, not CNs, so a cert with SANs must
    never match through an unvalidated CN."""
    from cryptography import x509
    from cryptography.x509.oid import NameOID

    out: list[str] = []
    try:
        san = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        out += san.get_values_for_type(x509.RFC822Name)
        out += san.get_values_for_type(x509.UniformResourceIdentifier)
    except x509.ExtensionNotFound:
        pass
    if not out:
        for attr in cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME):
            value = attr.value
            out.append(value.decode() if isinstance(value, bytes) else value)
    return out


def subject_matches(cert, subject: str) -> bool:
    """cosign CertEmail equality, widened to the minio wildcard dialect
    the engine uses everywhere else (``*``/``?``), over every identity
    the certificate carries."""
    from ..utils.wildcard import wildcard_match

    return any(wildcard_match(subject, ident)
               for ident in cert_subjects(cert))


def verify_payload_signature(cert, payload: bytes, signature: bytes) -> bool:
    """Verify ``signature`` over ``payload`` with the certificate's
    public key (cosign signs SimpleSigning payloads with SHA-256)."""
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec, ed25519, padding, rsa

    key = cert.public_key()
    try:
        if isinstance(key, ec.EllipticCurvePublicKey):
            key.verify(signature, payload, ec.ECDSA(hashes.SHA256()))
        elif isinstance(key, rsa.RSAPublicKey):
            key.verify(signature, payload, padding.PKCS1v15(),
                       hashes.SHA256())
        elif isinstance(key, ed25519.Ed25519PublicKey):
            key.verify(signature, payload)
        else:
            return False
        return True
    except InvalidSignature:
        return False
