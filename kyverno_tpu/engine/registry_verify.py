"""OCI-registry image verifier: the network implementation of the
:class:`~kyverno_tpu.engine.image_verify.Verifier` seam.

Mirrors /root/reference/pkg/cosign/cosign.go:

- ``verify_signature`` (cosign.go:30 Verify + verifySignature): resolve
  the image's manifest digest, fetch the cosign signature object (tag
  ``sha256-<hex>.sig`` in the image repo, or the ``repository``
  override), ECDSA-P256-verify each layer's signature annotation over the
  SimpleSigning payload blob, and require the payload's
  ``critical.image.docker-manifest-digest`` to bind the resolved digest
  (the reference's payload check in cosign.go:77).
- ``fetch_attestations`` (cosign.go:103): fetch the ``.att`` object,
  verify each layer's DSSE envelope (PAE pre-authentication encoding over
  payloadType+payload), and return the decoded in-toto statements.

Transport is the Docker Registry HTTP API v2 over stdlib urllib with
token auth (401 + WWW-Authenticate: Bearer -> token exchange), so this
works against real registries; the test suite runs it against an
in-process registry stub speaking the same protocol.
"""

from __future__ import annotations

import base64
import hashlib
import json
import re
import urllib.error
import urllib.request

from ..utils import ecdsa
from .image_verify import VerificationError, Verifier

SIG_ANNOTATION = "dev.cosignproject.cosign/signature"
MANIFEST_ACCEPT = ", ".join([
    "application/vnd.oci.image.manifest.v1+json",
    "application/vnd.docker.distribution.manifest.v2+json",
])


def parse_image_ref(image: str, default_registry: str = "docker.io"):
    """image string -> (registry, repository, tag, digest)."""
    digest = ""
    if "@" in image:
        image, digest = image.split("@", 1)
    tag = ""
    head, _, last = image.rpartition("/")
    if ":" in last:
        last, tag = last.split(":", 1)
    name = f"{head}/{last}" if head else last

    parts = name.split("/")
    if len(parts) > 1 and ("." in parts[0] or ":" in parts[0]
                           or parts[0] == "localhost"):
        registry, repo = parts[0], "/".join(parts[1:])
    else:
        registry, repo = default_registry, name
    if registry == "docker.io" and "/" not in repo:
        repo = "library/" + repo      # official images live under library/
    return registry, repo, tag or ("" if digest else "latest"), digest


class RegistryClient:
    """Minimal Docker Registry API v2 client with Bearer token auth.

    The default timeout is deliberately tight: this client runs inside
    the synchronous admission path, and the Kubernetes webhook budget is
    10s (configmanager.go:33) — one slow registry must not eat it all."""

    def __init__(self, plain_http: bool = False, timeout_s: float = 5.0):
        self.plain_http = plain_http
        self.timeout_s = timeout_s
        # real registry tokens are scoped per repository; key accordingly
        self._tokens: dict[tuple[str, str], str] = {}

    def _base(self, registry: str) -> str:
        scheme = "http" if self.plain_http else "https"
        host = "registry-1.docker.io" if registry == "docker.io" else registry
        return f"{scheme}://{host}"

    @staticmethod
    def _repo_of(path: str) -> str:
        # /v2/<repo...>/{manifests|blobs}/<ref>
        parts = path.split("/")
        return "/".join(parts[2:-2]) if len(parts) >= 5 else ""

    def _get(self, registry: str, path: str, accept: str = "",
             _retried: bool = False):
        url = self._base(registry) + path
        req = urllib.request.Request(url)
        if accept:
            req.add_header("Accept", accept)
        token = self._tokens.get((registry, self._repo_of(path)))
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout_s)
        except urllib.error.HTTPError as e:
            if e.code == 401 and not _retried:
                # a cached token may be expired or scoped to another repo:
                # always re-exchange once, then give up
                self._tokens[(registry, self._repo_of(path))] = \
                    self._fetch_token(
                        registry, e.headers.get("WWW-Authenticate", ""))
                return self._get(registry, path, accept, _retried=True)
            raise VerificationError(
                f"registry GET {path} failed: HTTP {e.code}") from e
        except OSError as e:
            raise VerificationError(f"registry unreachable: {e}") from e
        with resp:
            return resp.read(), dict(resp.headers)

    def _fetch_token(self, registry: str, challenge: str) -> str:
        """Docker registry token exchange (Bearer realm=...,service=...)."""
        fields = dict(re.findall(r'(\w+)="([^"]*)"', challenge))
        realm = fields.get("realm")
        if not realm:
            raise VerificationError("unsupported auth challenge")
        params = "&".join(f"{k}={v}" for k, v in fields.items()
                          if k in ("service", "scope"))
        url = realm + ("?" + params if params else "")
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
                doc = json.loads(resp.read())
        except (OSError, ValueError) as e:
            raise VerificationError(f"token exchange failed: {e}") from e
        token = doc.get("token") or doc.get("access_token") or ""
        if not token:
            raise VerificationError("token endpoint returned no token")
        return token

    # --------------------------------------------------------------- API

    def manifest(self, registry: str, repo: str, ref: str):
        """(manifest dict, digest) for a tag or digest reference."""
        body, headers = self._get(
            registry, f"/v2/{repo}/manifests/{ref}", MANIFEST_ACCEPT)
        # the digest is ALWAYS computed from the returned bytes (what
        # cosign does): trusting Docker-Content-Digest would let a
        # compromised registry claim a signed image's digest while
        # serving different manifest content. The header, when present,
        # is only cross-checked — a mismatch is a registry lying.
        digest = "sha256:" + hashlib.sha256(body).hexdigest()
        claimed = (headers.get("Docker-Content-Digest") or "").strip().lower()
        # only a sha256 claim is comparable; other algorithms (sha512:...)
        # are spec-legal and simply not cross-checked
        if claimed.startswith("sha256:") and claimed != digest:
            raise VerificationError(
                f"registry digest header {claimed} does not match "
                f"manifest content {digest} for {repo}")
        try:
            return json.loads(body), digest
        except ValueError as e:
            raise VerificationError(f"malformed manifest for {repo}") from e

    def blob(self, registry: str, repo: str, digest: str) -> bytes:
        body, _ = self._get(registry, f"/v2/{repo}/blobs/{digest}")
        if ("sha256:" + hashlib.sha256(body).hexdigest()) != digest:
            raise VerificationError(f"blob digest mismatch for {digest}")
        return body


class RegistryVerifier(Verifier):
    """Key-based cosign verification against a live registry.

    Successful verifications cache for ``cache_ttl_s``: admission bursts
    re-verify the same (image, key) pair, and each network verification
    is 2-4 registry round trips inside the webhook budget."""

    def __init__(self, client: RegistryClient | None = None,
                 default_registry: str = "docker.io",
                 cache_ttl_s: float = 60.0):
        self.client = client or RegistryClient()
        self.default_registry = default_registry
        self.cache_ttl_s = cache_ttl_s
        self._cache: dict[tuple, tuple[float, object]] = {}

    # ------------------------------------------------------------ helpers

    def _cached(self, key: tuple):
        import time

        hit = self._cache.get(key)
        if hit is not None and hit[0] > time.monotonic():
            return hit[1]
        return None

    def _remember(self, key: tuple, value):
        import time

        self._cache[key] = (time.monotonic() + self.cache_ttl_s, value)
        if len(self._cache) > 4096:
            now = time.monotonic()
            self._cache = {k: v for k, v in self._cache.items()
                           if v[0] > now}
        return value

    def _resolve(self, image: str):
        registry, repo, tag, digest = parse_image_ref(
            image, self.default_registry)
        if not digest:
            _, digest = self.client.manifest(registry, repo, tag)
        return registry, repo, digest

    def _cosign_ref(self, registry: str, repo: str, digest: str, suffix: str,
                    repository: str) -> tuple[str, str, str]:
        """(registry, repo, tag) of the cosign object; ``repository``
        overrides the store location (imageVerify's repository field),
        including a cross-registry override."""
        tag = digest.replace("sha256:", "sha256-") + "." + suffix
        if repository:
            rreg, rrepo, _, _ = parse_image_ref(
                repository, self.default_registry)
            return rreg, rrepo, tag
        return registry, repo, tag

    def _load_key(self, key: str):
        if not key or "BEGIN PUBLIC KEY" not in key:
            raise VerificationError(
                "a PEM public key is required (keyless verification "
                "requires a Fulcio/Rekor deployment)")
        try:
            return ecdsa.load_public_key_pem(key)
        except ValueError as e:
            raise VerificationError(f"invalid public key: {e}") from e

    def _layers(self, registry: str, repo: str, tag: str):
        try:
            manifest, _ = self.client.manifest(registry, repo, tag)
        except VerificationError as e:
            raise VerificationError(f"no cosign object at {repo}:{tag} "
                                    f"({e})") from e
        return manifest.get("layers") or []

    # ---------------------------------------------------------------- API

    def verify_signature(self, image: str, key: str = "", repository: str = "",
                         roots: str = "", subject: str = "") -> str:
        """Key-based OR cert-chain ("keyless") verification, mirroring
        the reference's branch (pkg/cosign/cosign.go:80-89: a key uses
        it directly; otherwise Roots become the trust pool and Subject
        the certificate identity check, pkg/engine/imageVerify.go:176).
        A policy must supply one of the two — the hosted Fulcio root
        cosign would default to is not reachable from this engine."""
        cache_key = ("sig", image, key, repository, roots, subject)
        hit = self._cached(cache_key)
        if hit is not None:
            return hit
        if key:
            check_layer = self._key_checker(key)
        elif roots:
            check_layer = self._cert_chain_checker(roots, subject)
        else:
            raise VerificationError(
                "either a public key or trust roots are required "
                "(hosted-Fulcio keyless needs a Fulcio deployment)")
        registry, repo, digest = self._resolve(image)
        sig_reg, sig_repo, sig_tag = self._cosign_ref(
            registry, repo, digest, "sig", repository)

        layers = self._layers(sig_reg, sig_repo, sig_tag)
        if not layers:
            raise VerificationError(f"no signatures found for {image}")
        errors = []
        for layer in layers:
            b64sig = (layer.get("annotations") or {}).get(SIG_ANNOTATION, "")
            if not b64sig:
                continue
            try:
                payload = self.client.blob(
                    sig_reg, sig_repo, layer.get("digest", ""))
                sig = base64.b64decode(b64sig)
            except (VerificationError, ValueError) as e:
                errors.append(str(e))
                continue
            err = check_layer(layer, payload, sig)
            if err:
                errors.append(err)
                continue
            # the payload must bind the digest we resolved (cosign.go:77)
            try:
                bound = (json.loads(payload).get("critical", {})
                         .get("image", {}).get("docker-manifest-digest", ""))
            except ValueError:
                errors.append("malformed signature payload")
                continue
            if bound != digest:
                errors.append(
                    f"payload binds {bound}, manifest digest is {digest}")
                continue
            return self._remember(cache_key, digest)
        raise VerificationError(
            f"no valid signature for {image}: {'; '.join(errors) or 'none'}")

    def _key_checker(self, key: str):
        """Layer check for the bare-public-key path (ECDSA P-256)."""
        pub = self._load_key(key)

        def check(layer, payload: bytes, sig: bytes):
            if not ecdsa.verify(pub, payload, sig):
                return "signature does not match key"
            return None

        return check

    def _cert_chain_checker(self, roots: str, subject: str):
        """Layer check for the cert-chain path: the signature layer's
        certificate chains to the supplied roots, its identity matches
        ``subject`` (when set), and its public key verifies the payload
        (engine/certchain.py; cosign keyless minus the tlog)."""
        from . import certchain

        try:
            root_certs = certchain.load_pem_certs(roots)
        except certchain.CertChainError as e:
            raise VerificationError(f"invalid roots: {e}") from e

        def check(layer, payload: bytes, sig: bytes):
            ann = layer.get("annotations") or {}
            cert_pem = ann.get(certchain.CERT_ANNOTATION, "")
            if not cert_pem:
                return "signature layer carries no certificate"
            try:
                leaf = certchain.load_pem_certs(cert_pem)[0]
                chain = (certchain.load_pem_certs(
                    ann[certchain.CHAIN_ANNOTATION])
                    if ann.get(certchain.CHAIN_ANNOTATION) else [])
                certchain.verify_chain(leaf, chain, root_certs)
            except certchain.CertChainError as e:
                return str(e)
            if subject and not certchain.subject_matches(leaf, subject):
                return (f"certificate identity "
                        f"{certchain.cert_subjects(leaf)} does not match "
                        f"subject {subject!r}")
            if not certchain.verify_payload_signature(leaf, payload, sig):
                return "signature does not match certificate key"
            return None

        return check

    def fetch_attestations(self, image: str, key: str = "",
                           repository: str = "", roots: str = "",
                           subject: str = "") -> list[dict]:
        """DSSE attestation statements, verified with a public key or —
        keyless — with the certificate on each attestation layer (chain
        to ``roots`` + ``subject`` identity), mirroring
        verify_signature's branch."""
        cache_key = ("att", image, key, repository, roots, subject)
        hit = self._cached(cache_key)
        if hit is not None:
            return list(hit)
        if key:
            check_layer = self._key_checker(key)
        elif roots:
            check_layer = self._cert_chain_checker(roots, subject)
        else:
            raise VerificationError(
                "either a public key or trust roots are required "
                "(hosted-Fulcio keyless needs a Fulcio deployment)")
        registry, repo, digest = self._resolve(image)
        att_reg, att_repo, att_tag = self._cosign_ref(
            registry, repo, digest, "att", repository)

        layers = self._layers(att_reg, att_repo, att_tag)
        if not layers:
            raise VerificationError(f"no attestations found for {image}")
        statements = []
        for layer in layers:
            envelope_raw = self.client.blob(
                att_reg, att_repo, layer.get("digest", ""))
            try:
                envelope = json.loads(envelope_raw)
                payload = base64.b64decode(envelope.get("payload", ""))
                pae = dsse_pae(envelope.get("payloadType", ""), payload)
                sigs = [base64.b64decode((s or {}).get("sig", ""))
                        for s in envelope.get("signatures") or []]
            except (ValueError, TypeError) as e:
                raise VerificationError(
                    f"malformed attestation envelope: {e}") from e
            errs = [check_layer(layer, pae, s) for s in sigs]
            if not any(e is None for e in errs):
                raise VerificationError(
                    "attestation signature verification failed for "
                    f"{image}: {'; '.join(e for e in errs if e) or 'no signatures'}")
            try:
                statement = json.loads(payload)
            except ValueError as e:
                raise VerificationError(
                    f"malformed in-toto statement: {e}") from e
            # the statement's subject must bind the image we resolved —
            # without this, a valid attestation from image A replays
            # under image B's .att tag
            if not _subject_binds(statement, digest):
                raise VerificationError(
                    f"attestation subject does not match {image} "
                    f"digest {digest}")
            statements.append(statement)
        self._remember(cache_key, statements)
        return list(statements)


def _subject_binds(statement: dict, digest: str) -> bool:
    """True when an in-toto statement's subject digest matches."""
    want = digest.split(":", 1)[-1]
    for subject in statement.get("subject") or []:
        got = ((subject or {}).get("digest") or {}).get("sha256", "")
        if got == want:
            return True
    return False


def dsse_pae(payload_type: str, payload: bytes) -> bytes:
    """DSSE pre-authentication encoding (the bytes actually signed)."""
    pt = payload_type.encode()
    return (b"DSSEv1 " + str(len(pt)).encode() + b" " + pt
            + b" " + str(len(payload)).encode() + b" " + payload)
