"""Accessors over untyped Kubernetes resource JSON (unstructured twin)."""

from __future__ import annotations


def get_kind(resource: dict) -> str:
    return resource.get("kind", "") or ""


def get_api_version(resource: dict) -> str:
    return resource.get("apiVersion", "") or ""


def get_name(resource: dict) -> str:
    return (resource.get("metadata") or {}).get("name", "") or ""


def get_namespace(resource: dict) -> str:
    return (resource.get("metadata") or {}).get("namespace", "") or ""


def get_labels(resource: dict) -> dict:
    return (resource.get("metadata") or {}).get("labels") or {}


def get_annotations(resource: dict) -> dict:
    return (resource.get("metadata") or {}).get("annotations") or {}


def get_uid(resource: dict) -> str:
    return (resource.get("metadata") or {}).get("uid", "") or ""


def gvk(resource: dict) -> tuple[str, str, str]:
    """(group, version, kind) from apiVersion + kind."""
    api_version = get_api_version(resource)
    if "/" in api_version:
        group, version = api_version.split("/", 1)
    else:
        group, version = "", api_version
    return group, version, get_kind(resource)


def title_first(s: str) -> str:
    """Go strings.Title on a single word: uppercase first rune, keep rest."""
    return (s[:1].upper() + s[1:]) if s else s
