"""Rule match/exclude filtering.

Mirrors /root/reference/pkg/engine/utils.go:265 MatchesResourceDescription:
AND across attributes of a resource filter, OR inside list attributes;
``any`` = OR over filters, ``all`` = AND; exclude mirrors match with
inverted effect. UserInfo (roles/clusterRoles/subjects) matches as OR.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..api.types import MatchResources, ResourceDescription, ResourceFilter, Rule, UserInfo
from ..utils.wildcard import wildcard_match
from . import resource as res
from .selector import SelectorError, selector_matches
from .wildcards import replace_in_selector

SA_PREFIX = "system:serviceaccount:"


@dataclass
class AdmissionUserInfo:
    username: str = ""
    uid: str = ""
    groups: list[str] = field(default_factory=list)


@dataclass
class RequestInfo:
    """kyverno.RequestInfo: resolved RBAC roles plus raw admission userInfo."""

    roles: list[str] = field(default_factory=list)
    cluster_roles: list[str] = field(default_factory=list)
    admission_user_info: AdmissionUserInfo = field(default_factory=AdmissionUserInfo)

    def is_empty(self) -> bool:
        return not (
            self.roles
            or self.cluster_roles
            or self.admission_user_info.username
            or self.admission_user_info.uid
            or self.admission_user_info.groups
        )


def check_kind(kinds: list[str], resource: dict) -> bool:
    """utils.go:38 checkKind: "Pod", "*", "v1/Pod", "apps/v1/Deployment"."""
    group, version, kind = res.gvk(resource)
    for k in kinds:
        parts = k.split("/")
        if len(parts) == 1:
            if kind == res.title_first(k) or k == "*":
                return True
        elif len(parts) == 2:
            if kind == res.title_first(parts[1]) and version == parts[0]:
                return True
        else:
            if (
                group == parts[0]
                and kind == res.title_first(parts[2])
                and (version == parts[1] or parts[1] == "*")
            ):
                return True
    return False


def check_name(pattern: str, name: str) -> bool:
    return wildcard_match(pattern, name)


def check_namespace(namespaces: list[str], resource: dict) -> bool:
    ns = res.get_namespace(resource)
    if res.get_kind(resource) == "Namespace":
        ns = res.get_name(resource)
    return any(wildcard_match(p, ns) for p in namespaces)


def check_annotations(annotations: dict, resource_annotations: dict) -> bool:
    """Every pattern entry must match some resource annotation (utils.go:78)."""
    for k, v in annotations.items():
        if not any(
            wildcard_match(k, rk) and wildcard_match(str(v), str(rv))
            for rk, rv in resource_annotations.items()
        ):
            return False
    return True


def check_selector(selector: dict, resource_labels: dict) -> tuple[bool, str]:
    sel = dict(selector)
    if sel.get("matchLabels"):
        sel["matchLabels"] = replace_in_selector(sel["matchLabels"], resource_labels)
    try:
        return selector_matches(sel, resource_labels), ""
    except SelectorError as e:
        return False, str(e)


def match_subjects(subjects: list[dict], user: AdmissionUserInfo, dynamic_config: list[str]) -> bool:
    """utils.go:237 matchSubjects."""
    user_groups = list(user.groups) + [user.username]
    all_subjects = list(subjects) + [
        {"kind": "Group", "name": g} for g in dynamic_config
    ]
    for subject in all_subjects:
        kind = subject.get("kind", "")
        name = subject.get("name", "")
        if kind == "ServiceAccount":
            if len(user.username) <= len(SA_PREFIX):
                continue
            target = f"{subject.get('namespace', '')}:{name}"
            if user.username[len(SA_PREFIX):] == target:
                return True
        elif kind in ("User", "Group"):
            if name in user_groups:
                return True
    return False


def _check_condition_block(
    desc: ResourceDescription,
    user_info: UserInfo,
    admission_info: RequestInfo,
    resource: dict,
    dynamic_config: list[str],
    namespace_labels: dict,
) -> list[str]:
    """utils.go:124 doesResourceMatchConditionBlock: returns failure reasons."""
    errs: list[str] = []
    if desc.kinds and not check_kind(desc.kinds, resource):
        errs.append(f"kind does not match {desc.kinds}")
    if desc.name and not check_name(desc.name, res.get_name(resource)):
        errs.append("name does not match")
    if desc.names and not any(check_name(n, res.get_name(resource)) for n in desc.names):
        errs.append("none of the names match")
    if desc.namespaces and not check_namespace(desc.namespaces, resource):
        errs.append("namespace does not match")
    if desc.annotations and not check_annotations(desc.annotations, res.get_annotations(resource)):
        errs.append("annotations does not match")
    if desc.selector is not None:
        ok, err = check_selector(desc.selector, res.get_labels(resource))
        if err:
            errs.append(f"failed to parse selector: {err}")
        elif not ok:
            errs.append("selector does not match")
    if (
        desc.namespace_selector is not None
        and res.get_kind(resource) not in ("Namespace", "")
    ):
        ok, err = check_selector(desc.namespace_selector, namespace_labels)
        if err:
            errs.append(f"failed to parse namespace selector: {err}")
        elif not ok:
            errs.append("namespace selector does not match")

    # UserInfo: OR across roles / clusterRoles / subjects (utils.go:196-234)
    keys = list(admission_info.admission_user_info.groups) + [
        admission_info.admission_user_info.username
    ]
    excluded_by_config = any(k in keys for k in dynamic_config)
    user_errs: list[str] = []
    checked = 0
    if user_info.roles and not excluded_by_config:
        checked += 1
        if any(r in user_info.roles for r in admission_info.roles):
            return errs
        user_errs.append("user info does not match roles")
    if user_info.cluster_roles and not excluded_by_config:
        checked += 1
        if any(r in user_info.cluster_roles for r in admission_info.cluster_roles):
            return errs
        user_errs.append("user info does not match clusterRoles")
    if user_info.subjects:
        checked += 1
        if match_subjects(user_info.subjects, admission_info.admission_user_info, dynamic_config):
            return errs
        user_errs.append("user info does not match subjects")
    if checked != len(user_errs):
        return errs
    return errs + user_errs


def _match_helper(
    rf: ResourceFilter,
    admission_info: RequestInfo,
    resource: dict,
    dynamic_config: list[str],
    namespace_labels: dict,
) -> list[str]:
    user_info = rf.user_info
    if admission_info.is_empty():
        user_info = UserInfo()
    if rf.resources.is_empty() and user_info.is_empty():
        return ["match cannot be empty"]
    return _check_condition_block(
        rf.resources, user_info, admission_info, resource, dynamic_config, namespace_labels
    )


def _exclude_helper(
    rf: ResourceFilter,
    admission_info: RequestInfo,
    resource: dict,
    dynamic_config: list[str],
    namespace_labels: dict,
) -> list[str]:
    if rf.resources.is_empty() and rf.user_info.is_empty():
        return []
    errs = _check_condition_block(
        rf.resources, rf.user_info, admission_info, resource, dynamic_config, namespace_labels
    )
    if not errs:
        return ["resource excluded since one of the criteria excluded it"]
    return []


def matches_resource_description(
    resource: dict,
    rule: Rule,
    admission_info: RequestInfo | None = None,
    dynamic_config: list[str] | None = None,
    namespace_labels: dict | None = None,
    policy_namespace: str = "",
) -> tuple[bool, str]:
    """utils.go:265. Returns (matches, reason-if-not)."""
    admission_info = admission_info or RequestInfo()
    dynamic_config = dynamic_config or []
    namespace_labels = namespace_labels or {}
    reasons: list[str] = []

    if policy_namespace and policy_namespace != res.get_namespace(resource):
        return False, "policy and resource namespaces differ"

    match: MatchResources = rule.match
    if match.any:
        if not any(
            not _match_helper(rf, admission_info, resource, dynamic_config, namespace_labels)
            for rf in match.any
        ):
            reasons.append("no resource matched")
    elif match.all:
        for rf in match.all:
            reasons.extend(
                _match_helper(rf, admission_info, resource, dynamic_config, namespace_labels)
            )
    else:
        rf = ResourceFilter(user_info=match.user_info, resources=match.resources)
        reasons.extend(
            _match_helper(rf, admission_info, resource, dynamic_config, namespace_labels)
        )

    exclude: MatchResources = rule.exclude
    if exclude.any:
        for rf in exclude.any:
            reasons.extend(
                _exclude_helper(rf, admission_info, resource, dynamic_config, namespace_labels)
            )
    elif exclude.all:
        if all(
            _exclude_helper(rf, admission_info, resource, dynamic_config, namespace_labels)
            for rf in exclude.all
        ):
            reasons.append("resource excluded since all criteria exclude it")
    else:
        rf = ResourceFilter(user_info=exclude.user_info, resources=exclude.resources)
        reasons.extend(
            _exclude_helper(rf, admission_info, resource, dynamic_config, namespace_labels)
        )

    if reasons:
        return False, f"rule {rule.name} not matched: " + "; ".join(reasons)
    return True, ""
