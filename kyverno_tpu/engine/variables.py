"""Variable `{{...}}` and relative-reference `$(...)` substitution.

Mirrors /root/reference/pkg/engine/variables/vars.go: rewrites variables
anywhere in a rule (values AND map keys), resolving JMESPath expressions
against the JSON context, looping until no variables remain (variables may
resolve to strings containing more variables). Supports:

  - escaping:  \\{{...}} and \\$(...) pass through un-substituted
  - {{@}}    :  the value at the current position in request.object
  - DELETE requests rewrite request.object -> request.oldObject
  - $(../sibling) relative references with operator prefixes
  - preconditions resolver: unresolved variables become "" instead of errors

In the accelerated tier, rules whose variables depend only on
compile-time-known context evaluate once per (policy, request-class) at
compile time; request-object-dependent variables route the rule to the CPU
lane (SURVEY.md section 7 step 4).
"""

from __future__ import annotations

import json
import re

from .anchors import remove_anchors_from_path
from .context import Context, InvalidVariableError
from .jsonutils import traverse_leaves_and_keys
from .pattern import get_operator

REGEX_VARIABLES = re.compile(r"^\{\{[^{}]*\}\}|[^\\]\{\{[^{}]*\}\}")
REGEX_ESCP_VARIABLES = re.compile(r"\\\{\{[^{}]*\}\}")
REGEX_REFERENCES = re.compile(r"^\$\(.[^ ]*\)|[^\\]\$\(.[^ ]*\)")
REGEX_ESCP_REFERENCES = re.compile(r"\\\$\(.[^ ]*\)")
REGEX_VARIABLE_INIT = re.compile(r"^\{\{[^{}]*\}\}")
_REGEX_PATH_DIGIT = re.compile(r"\.?(\d)\.?")


class VariableResolutionError(Exception):
    def __init__(self, variable: str, path: str, reason: str = ""):
        self.variable = variable
        self.path = path
        super().__init__(
            f"failed to resolve {variable} at path {path}"
            + (f": {reason}" if reason else "")
        )


class NotResolvedReferenceError(VariableResolutionError):
    pass


def is_variable(value: str) -> bool:
    return bool(REGEX_VARIABLES.findall(value))


def is_reference(value: str) -> bool:
    return bool(REGEX_REFERENCES.findall(value))


def _find_all(regex: re.Pattern, s: str) -> list[str]:
    """re.findall with groups disabled — we need whole matches, and Go's
    FindAllString semantics (non-overlapping, leftmost)."""
    return [m.group(0) for m in regex.finditer(s)]


def default_resolver(ctx: Context, variable: str):
    return ctx.query(variable)


def preconditions_resolver(ctx: Context, variable: str):
    """vars.go:62: unresolved precondition variables become empty strings."""
    try:
        value = ctx.query(variable)
    except InvalidVariableError:
        return ""
    return value


def substitute_all(ctx: Context, document, resolver=default_resolver):
    """vars.go:78 SubstituteAll: references first, then variables."""
    document = substitute_references(document)
    return substitute_vars(ctx, document, resolver)


def substitute_all_in_preconditions(ctx: Context, document):
    return substitute_all(ctx, document, preconditions_resolver)


def substitute_all_force_mutate(ctx: Context | None, document):
    """vars.go:182 SubstituteAllForceMutate (CLI dry-runs): references, then
    either real substitution or placeholder replacement when no context."""
    document = substitute_references(document)
    if ctx is None:
        return _replace_with_placeholders(document)
    return substitute_vars(ctx, document, default_resolver)


def _replace_with_placeholders(document):
    raw = json.dumps(document)
    regex = re.compile(r"\{\{[^{}]*\}\}")
    while regex.search(raw):
        raw = regex.sub("placeholderValue", raw)
    return json.loads(raw)


def substitute_vars(ctx: Context, document, resolver=default_resolver):
    is_delete = _is_delete_request(ctx)

    def action(element, path, doc):
        if not isinstance(element, str):
            return element
        value = element
        variables = _find_all(REGEX_VARIABLES, value)
        while variables:
            original = value
            for var_match in variables:
                initial = bool(REGEX_VARIABLE_INIT.match(var_match))
                old = var_match
                v = var_match if initial else var_match[1:]
                variable = v.replace("{{", "").replace("}}", "").strip()

                if variable == "@":
                    jp = _get_jmespath(path)
                    if jp.startswith("["):
                        variable = f"request.object{jp}"
                    else:
                        variable = f"request.object.{jp}" if jp else "request.object"
                if is_delete:
                    variable = variable.replace("request.object", "request.oldObject")

                try:
                    substituted = resolver(ctx, variable)
                except InvalidVariableError as e:
                    raise VariableResolutionError(variable, path, str(e))

                if original == v:
                    # the whole string was one variable: keep the JSON type
                    return substituted

                prefix = "" if initial else old[0]
                value = _substitute_in_pattern(prefix, value, v, substituted)
            variables = _find_all(REGEX_VARIABLES, value)

        for esc in _find_all(REGEX_ESCP_VARIABLES, value):
            value = value.replace(esc, esc[1:])
        return value

    return traverse_leaves_and_keys(document, action)


def _substitute_in_pattern(prefix: str, pattern: str, variable: str, value) -> str:
    if isinstance(value, str):
        s = value
    else:
        s = json.dumps(value, separators=(",", ":"))
    return pattern.replace(prefix + variable, prefix + s, 1)


def _is_delete_request(ctx: Context | None) -> bool:
    if ctx is None:
        return False
    try:
        return ctx.query("request.operation") == "DELETE"
    except InvalidVariableError:
        return False


def _get_jmespath(raw_path: str) -> str:
    """vars.go:415 getJMESPath: strip the rule-prefix (first 3 segments,
    e.g. /validate/pattern) and convert to JMESPath with [n] indexes."""
    tokens = raw_path.split("/")[3:]
    path = ".".join(tokens)
    path = _REGEX_PATH_DIGIT.sub(r"[\1].", path)
    return path.strip(".")


# -------------------------------------------------------------- references


def substitute_references(document):
    """$(...) sibling references resolved against the document itself."""

    def action(element, path, doc):
        if not isinstance(element, str):
            return element
        value = element
        for ref_match in _find_all(REGEX_REFERENCES, value):
            initial = ref_match.startswith("$(")
            old = ref_match
            v = ref_match if initial else ref_match[1:]

            resolved = _resolve_reference(doc, v, path)
            if resolved is None:
                raise NotResolvedReferenceError(v, path)
            if isinstance(resolved, str):
                replacement = ("" if initial else old[0]) + resolved
                value = value.replace(old, replacement, 1)
                continue
            raise NotResolvedReferenceError(v, path)

        for esc in _find_all(REGEX_ESCP_REFERENCES, value):
            value = value.replace(esc, esc[1:])
        return value

    return traverse_leaves_and_keys(document, action)


def _resolve_reference(full_document, reference: str, absolute_path: str):
    """vars.go:450 resolveReference: relative path -> absolute, fetch value,
    re-apply any operator prefix."""
    path = reference.strip("$()")
    operation = get_operator(path)
    path = path[len(operation.value):]
    if not path:
        raise VariableResolutionError(reference, absolute_path, "empty reference")

    path = _form_absolute_path(path, absolute_path)
    value = _get_value_from_reference(full_document, path)
    if operation.value == "":
        return value
    if isinstance(value, str):
        return operation.value + value
    if isinstance(value, bool):
        raise VariableResolutionError(reference, absolute_path, "non-scalar reference")
    if isinstance(value, int):
        return operation.value + str(value)
    if isinstance(value, float):
        return operation.value + f"{value:f}"
    raise VariableResolutionError(reference, absolute_path, "non-scalar reference")


def _form_absolute_path(reference_path: str, absolute_path: str) -> str:
    if reference_path.startswith("/"):
        return _normalize(reference_path)
    return _normalize(f"{absolute_path}/{reference_path}")


def _normalize(path: str) -> str:
    parts: list[str] = []
    for seg in path.split("/"):
        if seg in ("", "."):
            continue
        if seg == "..":
            if parts:
                parts.pop()
        else:
            parts.append(seg)
    return "/" + "/".join(parts)


def _get_value_from_reference(document, path: str):
    found = []

    def action(element, elem_path, doc):
        if remove_anchors_from_path(elem_path) == path and not found:
            found.append(element)
        return element

    traverse_leaves_and_keys(document, action)
    return found[0] if found else None


def replace_all_vars(src: str, repl) -> str:
    """vars.go:46 ReplaceAllVars — rewrite each {{var}} via ``repl``."""

    def wrapper(m: re.Match) -> str:
        s = m.group(0)
        prefix = ""
        if not REGEX_VARIABLE_INIT.match(s):
            prefix, s = s[0], s[1:]
        return prefix + repl(s)

    return REGEX_VARIABLES.sub(wrapper, src)
