"""Engine response model (mirrors /root/reference/pkg/engine/response)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum


class RuleStatus(Enum):
    """response/status.go:10-27"""

    PASS = "pass"
    FAIL = "fail"
    WARN = "warn"
    ERROR = "error"
    SKIP = "skip"


class RuleType(Enum):
    MUTATION = "Mutation"
    VALIDATION = "Validation"
    GENERATION = "Generation"
    IMAGE_VERIFY = "ImageVerify"


@dataclass
class RuleResponse:
    """response/response.go:72"""

    name: str
    type: RuleType
    message: str = ""
    status: RuleStatus = RuleStatus.PASS
    patches: list = field(default_factory=list)  # RFC6902 ops (dicts)
    generated_resource: dict | None = None
    processing_time_s: float = 0.0

    @property
    def success(self) -> bool:
        return self.status in (RuleStatus.PASS, RuleStatus.SKIP, RuleStatus.WARN)


@dataclass
class PolicySpecSummary:
    name: str = ""
    category: str = ""
    validation_failure_action: str = "audit"


@dataclass
class ResourceSpec:
    kind: str = ""
    api_version: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""


@dataclass
class PolicyResponse:
    """response/response.go:19"""

    policy: PolicySpecSummary = field(default_factory=PolicySpecSummary)
    resource: ResourceSpec = field(default_factory=ResourceSpec)
    rules: list[RuleResponse] = field(default_factory=list)
    rules_applied_count: int = 0
    rules_error_count: int = 0
    processing_time_s: float = 0.0
    timestamp: float = field(default_factory=time.time)


@dataclass
class EngineResponse:
    """response/response.go:11"""

    patched_resource: dict | None = None
    policy_response: PolicyResponse = field(default_factory=PolicyResponse)

    @property
    def successful(self) -> bool:
        """response/response.go:107 IsSuccessful: no rule failed or errored."""
        return all(r.success for r in self.policy_response.rules)

    @property
    def patches(self) -> list:
        out = []
        for r in self.policy_response.rules:
            out.extend(r.patches)
        return out

    def get_failed_rules(self) -> list[str]:
        return [
            r.name
            for r in self.policy_response.rules
            if r.status in (RuleStatus.FAIL, RuleStatus.ERROR)
        ]
