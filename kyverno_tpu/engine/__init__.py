"""The policy engine: pure functions of (policy, resource, context) -> response.

This is the CPU oracle tier. The accelerated tier (``kyverno_tpu.models`` +
``kyverno_tpu.ops``) compiles the same semantics into batched JAX kernels and
is cross-checked against this package test-for-test.
"""

from .response import EngineResponse, RuleResponse, RuleStatus, RuleType

__all__ = ["EngineResponse", "RuleResponse", "RuleStatus", "RuleType"]
