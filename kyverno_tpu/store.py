"""Mock context store for offline (CLI / test) engine runs.

Mirrors /root/reference/pkg/kyverno/store/store.go: when mock mode is on,
``load_context`` (engine/json_context_loader.py) resolves a rule's external
``context:`` entries from values declared here instead of hitting a live
cluster — the branch at /root/reference/pkg/engine/jsonContext.go:27-48.
"""

from __future__ import annotations

from dataclasses import dataclass, field

_mock: bool = False
_context: "Context | None" = None


@dataclass
class Rule:
    """store.go Rule: per-rule declared variable values."""

    name: str = ""
    values: dict[str, str] = field(default_factory=dict)


@dataclass
class Policy:
    name: str = ""
    rules: list[Rule] = field(default_factory=list)


@dataclass
class Context:
    policies: list[Policy] = field(default_factory=list)


def set_mock(mock: bool) -> None:
    global _mock
    _mock = mock


def get_mock() -> bool:
    return _mock


def set_context(ctx: Context) -> None:
    global _context
    _context = ctx


def get_policy_rule_from_context(policy_name: str, rule_name: str) -> Rule | None:
    """store.go GetPolicyRuleFromContext."""
    if _context is None:
        return None
    for policy in _context.policies:
        if policy.name != policy_name:
            continue
        for rule in policy.rules:
            if rule.name == rule_name:
                return rule
    return None
