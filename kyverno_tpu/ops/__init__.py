"""JAX kernels for the TPU policy engine: the vectorized glob-NFA string
matcher and the batched verdict reduction."""

from .glob import glob_match_matrix
from .eval import build_eval_fn

__all__ = ["glob_match_matrix", "build_eval_fn"]
