"""Batched verdict evaluation: the policy x resource matrix in one jit.

Replaces the reference's per-(policy, resource) recursive tree walk
(/root/reference/pkg/engine/validate/validate.go:29 MatchPattern) with a
fixed dataflow over the compiled check rows:

  1. glob-NFA over the string dictionary                    [N, V]
  2. per-check, per-slot leaf comparison + anchor masks     [B, C, E]
  3. element reduction (AND / existence-OR / gate open)     [B, C]
  4. group OR -> alternative AND -> rule verdict            [B, R]

All shapes are static; reductions are segment-sums over precomputed id
maps — no data-dependent control flow, everything fuses under jit.

Verdict codes (the Pass/Fail/Skip/Error lattice of
/root/reference/pkg/engine/response/status.go):
  0 = not applicable (kind prefilter miss / no rule response)
  1 = pass, 2 = fail, 3 = skip, 4 = error, 5 = host lane
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models.compiler import PolicyTensors
from ..models.ir import SEP, CheckOp
from .glob import glob_match_matrix

V_NOT_APPLICABLE, V_PASS, V_FAIL, V_SKIP, V_ERROR, V_HOST = range(6)

# type tags (mirror models/flatten.py)
T_ABSENT, T_NULL, T_BOOL, T_NUM, T_STR, T_OBJ, T_LIST = range(7)


def _limbs(n: np.ndarray):
    """Split i64 micro-units into (hi, lo) int32 limbs; lexicographic
    compare of (hi, lo) equals i64 compare (lo is non-negative)."""
    return ((n >> 31).astype(np.int32), (n & 0x7FFFFFFF).astype(np.int32))


def _lex_lt(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & (al < bl))


def _lex_eq(ah, al, bh, bl):
    return (ah == bh) & (al == bl)


def _segment_or(values, segment_ids, num_segments):
    """OR-reduce [C, ...] bool rows into segments."""
    return jax.ops.segment_max(values.astype(jnp.int8), segment_ids,
                               num_segments=num_segments) > 0


def _segment_and(values, segment_ids, num_segments):
    return jax.ops.segment_min(values.astype(jnp.int8), segment_ids,
                               num_segments=num_segments) > 0


def build_eval_fn(tensors: PolicyTensors, jit: bool = True):
    """Close over the static policy tensors; returns a jit'd function of the
    flattened batch. Static data lands in the jaxpr as constants, so XLA
    folds the per-check dispatch into straight-line vector code."""

    path_len = np.array([len(p.split(SEP)) for p in tensors.paths], dtype=np.int32)

    # per-check static columns
    c_path = jnp.asarray(tensors.chk_path)
    c_op = jnp.asarray(tensors.chk_op.astype(np.int32))
    c_plen = jnp.asarray(path_len[tensors.chk_path])
    c_guard = jnp.asarray(tensors.chk_guard.astype(np.int32))
    c_nfa = jnp.asarray(np.maximum(tensors.chk_nfa, 0))
    c_has_nfa = jnp.asarray(tensors.chk_nfa >= 0)
    c_lo_h, c_lo_l = (jnp.asarray(x) for x in _limbs(tensors.chk_num_lo))
    c_hi_h, c_hi_l = (jnp.asarray(x) for x in _limbs(tensors.chk_num_hi))
    c_bool = jnp.asarray(tensors.chk_bool)
    c_numfb = jnp.asarray(tensors.chk_num_fallback)
    c_gate = jnp.asarray(tensors.chk_gate)
    c_is_gate = jnp.asarray(tensors.chk_is_gate_row)
    c_is_cond = jnp.asarray(tensors.chk_is_cond)
    c_exist = jnp.asarray(tensors.chk_existence)
    c_track = jnp.asarray(tensors.chk_track_depth.astype(np.int32))
    c_alt = jnp.asarray(tensors.chk_alt_gid)
    c_group = jnp.asarray(tensors.chk_group_gid)
    c_cond_depth = jnp.asarray(tensors.chk_cond_depth.astype(np.int32))

    group_alt = jnp.asarray(tensors.group_alt)
    alt_rule = jnp.asarray(tensors.alt_rule)
    alt_is_multi = jnp.asarray(
        np.bincount(tensors.alt_rule, minlength=tensors.n_rules)[tensors.alt_rule] > 1
        if tensors.n_alts else np.zeros(0, dtype=bool)
    )

    rule_kind_ids = jnp.asarray(tensors.rule_kind_ids)
    rule_all_kinds = jnp.asarray(tensors.rule_match_all_kinds)
    rule_host = jnp.asarray(tensors.rule_host_only)

    nfa_char = jnp.asarray(tensors.nfa_char)
    nfa_star = jnp.asarray(tensors.nfa_is_star)
    nfa_q = jnp.asarray(tensors.nfa_is_q)
    nfa_len = jnp.asarray(tensors.nfa_len)

    n_groups = max(tensors.n_groups, 1)
    n_alts = max(tensors.n_alts, 1)
    n_rules = max(tensors.n_rules, 1)
    n_gates = max(tensors.n_gates, 1)

    def evaluate(mask, slot_valid, type_tag, str_id, num_hi, num_lo, num_ok,
                 bool_val, elem0, kind_id, host_flag, str_bytes, str_len):
        B = mask.shape[0]
        C = c_path.shape[0]
        E = mask.shape[2]

        # ---- stage 1: string dictionary vs glob patterns
        match_nv = glob_match_matrix(nfa_char, nfa_star, nfa_q, nfa_len,
                                     str_bytes, str_len)
        empty_str = str_len == 0                              # for IS_NULL

        # ---- stage 2: gather slots per check  [B, C, E]
        def g(x):
            return jnp.take(x, c_path, axis=1)

        mask_c = g(mask).astype(jnp.int32)
        valid_c = g(slot_valid)
        type_c = g(type_tag).astype(jnp.int32)
        sid_c = g(str_id)
        numh_c = g(num_hi)
        numl_c = g(num_lo)
        numok_c = g(num_ok)
        bool_c = g(bool_val)
        elem0_c = g(elem0)

        # chain analysis per slot: bits 1..plen must be present; the FIRST
        # absent bit decides the outcome (fail, or pass when that depth is
        # equality-guarded; leaf depth is an implicit guard for ABSENT)
        leaf_bit = (1 << c_plen)[None, :, None]
        want_bits = (leaf_bit << 1) - 2
        absent_bits = (~mask_c) & want_bits
        first_absent = absent_bits & (-absent_bits)
        leaf_present = absent_bits == 0
        guard_pass = (first_absent & c_guard[None, :, None]) != 0

        # string match: gather by dictionary id (id -1 -> no string form)
        has_sid = sid_c >= 0
        str_hit = match_nv[c_nfa[None, :, None], jnp.maximum(sid_c, 0)] & has_sid & c_has_nfa[None, :, None]
        # value stringification exists only for str/bool/num leaves
        stringy = (type_c == T_STR) | (type_c == T_BOOL) | (type_c == T_NUM)

        lo_h, lo_l = c_lo_h[None, :, None], c_lo_l[None, :, None]
        hi_h, hi_l = c_hi_h[None, :, None], c_hi_l[None, :, None]
        ge_lo = ~_lex_lt(numh_c, numl_c, lo_h, lo_l)
        le_hi = ~_lex_lt(hi_h, hi_l, numh_c, numl_c)
        gt_lo = _lex_lt(lo_h, lo_l, numh_c, numl_c)
        lt_lo = _lex_lt(numh_c, numl_c, lo_h, lo_l)
        eq_lo = _lex_eq(numh_c, numl_c, lo_h, lo_l)
        in_range = ge_lo & le_hi
        num_eq = numok_c & in_range
        use_num = c_numfb[None, :, None] & numok_c

        str_eq_ok = jnp.where(use_num, num_eq, stringy & str_hit)

        op = c_op[None, :, None]
        value_ok = jnp.select(
            [
                op == CheckOp.STR_EQ,
                op == CheckOp.STR_NE,
                op == CheckOp.NUM_EQ,
                op == CheckOp.NUM_NE,
                op == CheckOp.NUM_GT,
                op == CheckOp.NUM_GE,
                op == CheckOp.NUM_LT,
                op == CheckOp.NUM_LE,
                op == CheckOp.NUM_IN_RANGE,
                op == CheckOp.NUM_NOT_IN_RANGE,
                op == CheckOp.BOOL_EQ,
                op == CheckOp.IS_NULL,
                op == CheckOp.EXISTS_OBJECT,
                op == CheckOp.ABSENT,
            ],
            [
                str_eq_ok,
                stringy & ~str_eq_ok,
                numok_c & eq_lo,
                numok_c & ~eq_lo,
                numok_c & gt_lo,
                numok_c & ge_lo,
                numok_c & lt_lo,
                numok_c & ~gt_lo,
                num_eq,
                numok_c & ~in_range,
                (type_c == T_BOOL) & (bool_c == c_bool[None, :, None]),
                (type_c == T_NULL)
                | ((type_c == T_BOOL) & ~bool_c)
                | (numok_c & (numh_c == 0) & (numl_c == 0))
                | ((type_c == T_STR) & empty_str[jnp.maximum(sid_c, 0)] & has_sid),
                type_c == T_OBJ,
                jnp.ones_like(leaf_present),  # handled below
            ],
            default=jnp.zeros_like(leaf_present),
        )

        absent_ok = ~leaf_present & (
            (first_absent & (c_guard[None, :, None] | leaf_bit)) != 0
        )
        slot_ok = jnp.where(
            op == CheckOp.ABSENT,
            absent_ok,
            jnp.where(leaf_present, value_ok, guard_pass),
        )

        # ---- gates: per-element condition anchors in lists
        gate_row_open = ~leaf_present | value_ok              # absent key opens
        gate_rows = jnp.where(
            c_is_gate[None, :, None],
            gate_row_open | ~valid_c,
            jnp.ones_like(gate_row_open),
        )
        # reduce gate rows -> gate_open [B, G, E0max]; gate rows have one
        # wildcard so slot index == element index
        gate_seg = jnp.where(c_is_gate, c_gate, n_gates)      # dump non-gates
        gate_open = _segment_and(
            gate_rows.swapaxes(0, 1).reshape(C, -1), gate_seg, n_gates + 1
        )[:n_gates].reshape(n_gates, B, E)

        # gather gate state for gated checks by top-level element index
        has_gate = c_gate >= 0
        gate_idx = jnp.maximum(c_gate, 0)
        e0 = jnp.clip(elem0_c, 0, E - 1)
        gate_for_slot = gate_open[gate_idx[None, :, None],
                                  jnp.arange(B)[:, None, None], e0]
        gate_skips = has_gate[None, :, None] & (elem0_c >= 0) & ~gate_for_slot

        slot_ok = jnp.where(gate_skips, True, slot_ok)

        # ---- stage 3: element reduction
        and_ok = (slot_ok | ~valid_c).all(axis=2)
        or_ok = (slot_ok & valid_c & leaf_present).any(axis=2)
        check_ok = jnp.where(c_exist[None, :], or_ok, and_ok)   # [B, C]

        # condition rows: key present & predicate failed -> skip; an absent
        # ANCESTOR of the key is a plain pattern failure (the walk never
        # reaches the anchor), not a skip
        cond_bit = (1 << jnp.maximum(c_cond_depth, 0))[None, :, None]
        cond_key_present = (mask_c & cond_bit) != 0
        cond_fail_slot = cond_key_present & ~(leaf_present & value_ok) & valid_c
        cond_fail = (c_is_cond[None, :] & cond_fail_slot.any(axis=2))
        cond_chain_fail_slot = (first_absent != 0) & (first_absent < cond_bit) & valid_c
        cond_chain_fail = (c_is_cond[None, :] & cond_chain_fail_slot.any(axis=2))

        # anchorMap tracking: tracked key never present while its parent was
        # validated -> fail becomes error (common/anchorKey.go:94)
        tr = c_track[None, :, None]
        tr_parent = (mask_c >> jnp.maximum(tr - 1, 0)) & 1 > 0
        tr_present = (mask_c >> jnp.maximum(tr, 0)) & 1 > 0
        registered = ((c_track[None, :] >= 0)
                      & (tr_parent & valid_c).any(axis=2))
        anchor_missing = registered & ~(tr_present & valid_c).any(axis=2)

        # ---- stage 4: group / alt / rule reduction  (work in [C, B])
        seg_ok = check_ok.T
        # exclude gate + cond rows from the group AND (they are masks)
        is_plain = ~(c_is_gate | c_is_cond)
        plain_seg = jnp.where(is_plain, c_group, n_groups)
        group_ok = _segment_and(jnp.where(is_plain[:, None], seg_ok, True),
                                plain_seg, n_groups + 1)[:n_groups]  # [G, B]
        alt_ok = _segment_and(group_ok, group_alt, n_alts)            # [A, B]

        cond_seg = jnp.where(c_is_cond, c_alt, n_alts)
        alt_skip = _segment_or(jnp.where(c_is_cond[:, None], cond_fail.T, False),
                               cond_seg, n_alts + 1)[:n_alts]
        alt_chain_fail = _segment_or(
            jnp.where(c_is_cond[:, None], cond_chain_fail.T, False),
            cond_seg, n_alts + 1)[:n_alts]
        alt_ok = alt_ok & ~alt_chain_fail

        track_seg = jnp.where(c_track >= 0, c_alt, n_alts)
        alt_missing = _segment_or(
            jnp.where((c_track >= 0)[:, None], anchor_missing.T, False),
            track_seg, n_alts + 1,
        )[:n_alts]

        # per-alt verdict
        alt_verdict = jnp.where(
            alt_skip, V_SKIP,
            jnp.where(alt_ok, V_PASS,
                      jnp.where(alt_missing, V_ERROR, V_FAIL)))

        # single-pattern rules: verdict = the alt verdict.
        # anyPattern rules: any pass -> pass, else fail (skips/errors are
        # folded into the failure list, validation.go:448-480)
        alt_pass = alt_verdict == V_PASS
        rule_pass = _segment_or(alt_pass, alt_rule, n_rules)
        single_verdict = jax.ops.segment_max(
            jnp.where(alt_is_multi[:, None], 0, alt_verdict),
            alt_rule, num_segments=n_rules)
        multi = jax.ops.segment_max(alt_is_multi[:, None].astype(jnp.int32) *
                                    jnp.ones((n_alts, B), jnp.int32),
                                    alt_rule, num_segments=n_rules) > 0
        verdict = jnp.where(
            multi, jnp.where(rule_pass, V_PASS, V_FAIL), single_verdict
        ).T.astype(jnp.int8)                                   # [B, R]

        # gate rows whose key is absent in some element reproduce the
        # reference's first-failing-element anchorMap order dependency
        # (validateArrayOfMaps stops at the first non-conditional error);
        # a failing verdict there is resolved by the CPU oracle instead
        gate_key_absent = (c_is_gate[None, :] &
                           (~leaf_present & valid_c & (elem0_c >= 0)).any(axis=2))
        rule_seg = jnp.where(c_is_gate, jnp.asarray(tensors.chk_rule), n_rules)
        rule_gate_uncertain = _segment_or(
            gate_key_absent.T, rule_seg, n_rules + 1)[:n_rules].T  # [B, R]

        # rules with no device rows (host-only) or no alts at all
        covered = jnp.zeros(n_rules, bool).at[alt_rule].set(True)
        verdict = jnp.where(rule_host[None, :], V_HOST, verdict)
        verdict = jnp.where((~covered & ~rule_host)[None, :], V_NOT_APPLICABLE, verdict)

        # kind prefilter: resource kind must be in the rule's kind set
        kind_hit = (rule_kind_ids[None, :, :] == kind_id[:, None, None]).any(-1)
        applicable = kind_hit | rule_all_kinds[None, :]
        verdict = jnp.where(applicable, verdict, V_NOT_APPLICABLE)

        verdict = jnp.where(
            rule_gate_uncertain & ((verdict == V_FAIL) | (verdict == V_ERROR)),
            V_HOST, verdict)

        # resources flagged by the flattener take the host lane entirely
        verdict = jnp.where(host_flag[:, None] & (verdict != V_NOT_APPLICABLE),
                            V_HOST, verdict)
        return verdict

    return jax.jit(evaluate) if jit else evaluate
