"""Batched verdict evaluation: the policy x resource matrix in one jit.

Replaces the reference's per-(policy, resource) recursive tree walk
(/root/reference/pkg/engine/validate/validate.go:29 MatchPattern) with a
fixed dataflow over the compiled check rows:

  1. glob-NFA over the string dictionary                    [N, V]
  2. per-check, per-slot leaf comparison + anchor masks     [B, C, E]
  3. element reduction (AND / existence-OR / gate open)     [B, C]
  4. group OR -> alternative AND -> rule verdict            [B, R]
  5. aux programs: match/exclude filters, preconditions,
     deny conditions over the ax_* rows                     [B, X] -> [B, R]
  6. verdict composition: match miss -> NOT_APPLICABLE,
     failed precondition -> SKIP, met deny -> FAIL, deny
     key unresolved -> ERROR (utils.go:265 match semantics,
     variables/evaluate.go:11 conditions)

All shapes are static; reductions are segment-sums over precomputed id
maps — no data-dependent control flow, everything fuses under jit.

Verdict codes (the Pass/Fail/Skip/Error lattice of
/root/reference/pkg/engine/response/status.go):
  0 = not applicable (match miss / no rule response)
  1 = pass, 2 = fail, 3 = skip, 4 = error, 5 = host lane
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.compilecache import enable as _enable_compile_cache
from ..models.compiler import PolicyTensors
from ..models.ir import (
    AUX_DENY,
    AUX_EXCLUDE,
    AUX_MATCH,
    AUX_PRECOND,
    AuxOp,
    CheckOp,
    SEP,
)
from .glob import glob_match_matrix

V_NOT_APPLICABLE, V_PASS, V_FAIL, V_SKIP, V_ERROR, V_HOST = range(6)

_DEBUG = None  # set to a dict to return aux intermediates for debugging

# type tags (mirror models/flatten.py)
T_ABSENT, T_NULL, T_BOOL, T_NUM, T_STR, T_OBJ, T_LIST = range(7)


def _limbs(n: np.ndarray):
    """Split i64 micro-units into (hi, lo) int32 limbs; lexicographic
    compare of (hi, lo) equals i64 compare (lo is non-negative)."""
    return ((n >> 31).astype(np.int32), (n & 0x7FFFFFFF).astype(np.int32))


def _lex_lt(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & (al < bl))


def _lex_eq(ah, al, bh, bl):
    return (ah == bh) & (al == bl)


def _segment_or(values, segment_ids, num_segments):
    """OR-reduce [C, ...] bool rows into segments."""
    return jax.ops.segment_max(values.astype(jnp.int32), segment_ids,
                               num_segments=num_segments) > 0


def _segment_and(values, segment_ids, num_segments):
    return jax.ops.segment_min(values.astype(jnp.int32), segment_ids,
                               num_segments=num_segments) > 0


def build_eval_fn(tensors: PolicyTensors, jit: bool = True):
    """Close over the static policy tensors; returns a jit'd function of the
    flattened batch. Static data lands in the jaxpr as constants, so XLA
    folds the per-check dispatch into straight-line vector code."""

    # every jit path (packed/blob/scan/mesh) funnels through here, and a
    # real compile is imminent — the right moment for the persistent
    # compilation cache (accelerator backends only)
    _enable_compile_cache()

    path_len = np.array([len(p.split(SEP)) for p in tensors.paths], dtype=np.int32)

    # per-check static columns
    c_path = jnp.asarray(tensors.chk_path)
    c_op = jnp.asarray(tensors.chk_op.astype(np.int32))
    c_plen = jnp.asarray(path_len[tensors.chk_path])
    c_guard = jnp.asarray(tensors.chk_guard.astype(np.int32))
    c_nfa = jnp.asarray(np.maximum(tensors.chk_nfa, 0))
    c_has_nfa = jnp.asarray(tensors.chk_nfa >= 0)
    c_lo_h, c_lo_l = (jnp.asarray(x) for x in _limbs(tensors.chk_num_lo))
    c_hi_h, c_hi_l = (jnp.asarray(x) for x in _limbs(tensors.chk_num_hi))
    c_bool = jnp.asarray(tensors.chk_bool)
    c_numfb = jnp.asarray(tensors.chk_num_fallback)
    c_nummode = jnp.asarray(tensors.chk_num_mode.astype(np.int32))
    c_gate = jnp.asarray(tensors.chk_gate)
    c_is_gate = jnp.asarray(tensors.chk_is_gate_row)
    c_is_cond = jnp.asarray(tensors.chk_is_cond)
    c_exist = jnp.asarray(tensors.chk_existence)
    c_track = jnp.asarray(tensors.chk_track_depth.astype(np.int32))
    c_alt = jnp.asarray(tensors.chk_alt_gid)
    c_group = jnp.asarray(tensors.chk_group_gid)
    c_cond_depth = jnp.asarray(tensors.chk_cond_depth.astype(np.int32))

    group_alt = jnp.asarray(tensors.group_alt)
    alt_rule = jnp.asarray(tensors.alt_rule)
    alt_is_multi = jnp.asarray(
        np.bincount(tensors.alt_rule, minlength=tensors.n_rules)[tensors.alt_rule] > 1
        if tensors.n_alts else np.zeros(0, dtype=bool)
    )

    rule_kind_ids = jnp.asarray(tensors.rule_kind_ids)
    rule_all_kinds = jnp.asarray(tensors.rule_match_all_kinds)
    rule_host = jnp.asarray(tensors.rule_host_only)
    rule_deny = jnp.asarray(tensors.rule_is_deny)
    rule_deny_any = jnp.asarray(tensors.rule_deny_any)
    rule_precond_any = jnp.asarray(tensors.rule_precond_any)
    rule_match_any = jnp.asarray(tensors.rule_match_any)
    rule_has_match = jnp.asarray(tensors.rule_has_match)
    rule_has_exclude = jnp.asarray(tensors.rule_has_exclude)
    rule_exclude_all = jnp.asarray(tensors.rule_exclude_all)

    nfa_char = jnp.asarray(tensors.nfa_char)
    nfa_star = jnp.asarray(tensors.nfa_is_star)
    nfa_q = jnp.asarray(tensors.nfa_is_q)
    nfa_len = jnp.asarray(tensors.nfa_len)

    n_groups = max(tensors.n_groups, 1)
    n_alts = max(tensors.n_alts, 1)
    n_rules = max(tensors.n_rules, 1)
    n_gates = max(tensors.n_gates, 1)

    # static group-level maps: a compound "a | b" leaf splits into rows
    # sharing one group, so gate/cond state reduces rows-OR-in-group first,
    # then groups-AND within the gate / OR into the alt
    _gate_rows_np = np.asarray(tensors.chk_is_gate_row)
    _cond_rows_np = np.asarray(tensors.chk_is_cond)
    group_gate_np = np.full(n_groups, -1, dtype=np.int32)
    group_gate_np[tensors.chk_group_gid[_gate_rows_np]] = \
        tensors.chk_gate[_gate_rows_np]
    group_is_gate = jnp.asarray(group_gate_np >= 0)
    group_gate_seg = jnp.asarray(
        np.where(group_gate_np >= 0, group_gate_np, n_gates))
    cond_group_np = np.zeros(n_groups, dtype=bool)
    cond_group_np[tensors.chk_group_gid[_cond_rows_np]] = True
    cond_group = jnp.asarray(cond_group_np)
    has_plain_np = np.zeros(n_groups, dtype=bool)
    has_plain_np[tensors.chk_group_gid[~(_gate_rows_np | _cond_rows_np)]] = True
    has_plain = jnp.asarray(has_plain_np)

    # static: which rules have at least one device alternative (computed on
    # host — an on-device scatter over empty alt_rule aborts the TPU backend)
    covered_np = np.zeros(n_rules, dtype=bool)
    covered_np[tensors.alt_rule] = True
    covered = jnp.asarray(covered_np)

    # aux static columns (X rows — match/exclude/precondition/deny program)
    X = int(tensors.ax_op.size)
    n_axg = max(tensors.n_aux_groups, 1)
    n_axf = max(tensors.n_aux_filters, 1)
    if X:
        ax_klass_np = tensors.axg_klass[tensors.ax_group]
        x_path = jnp.asarray(np.maximum(tensors.ax_path, 0))
        x_has_path = jnp.asarray(tensors.ax_path >= 0)
        x_plen = jnp.asarray(tensors.ax_plen.astype(np.int32))
        x_op = jnp.asarray(tensors.ax_op.astype(np.int32))
        x_rule = jnp.asarray(tensors.ax_rule)
        x_group = jnp.asarray(tensors.ax_group)
        x_kind = jnp.asarray(tensors.ax_kind_req)
        x_nfa = jnp.asarray(np.maximum(tensors.ax_nfa, 0))
        x_has_nfa = jnp.asarray(tensors.ax_nfa >= 0)
        x_absent = jnp.asarray(tensors.ax_absent)
        x_err = jnp.asarray(tensors.ax_err_absent)
        x_allow_num = jnp.asarray(tensors.ax_allow_num)
        x_key_pat = jnp.asarray(tensors.ax_key_pat)
        x_obool = jnp.asarray(tensors.ax_obool)
        x_o_bool = jnp.asarray(tensors.ax_is_obool)
        x_o_str = jnp.asarray(tensors.ax_is_ostr)
        x_o_num = jnp.asarray(tensors.ax_is_onum)
        x_o_dur = jnp.asarray(tensors.ax_is_odur)
        x_o_float = jnp.asarray(tensors.ax_is_ofloat)
        x_o_int = jnp.asarray(tensors.ax_is_oint)
        x_o_quant = jnp.asarray(tensors.ax_is_oquant)
        x_q_h = jnp.asarray(tensors.ax_q_hi)
        x_q_l = jnp.asarray(tensors.ax_q_lo)
        x_s_h = jnp.asarray(tensors.ax_s_hi)
        x_s_l = jnp.asarray(tensors.ax_s_lo)
        x_is_match_klass = jnp.asarray(
            (ax_klass_np == AUX_MATCH) | (ax_klass_np == AUX_EXCLUDE))
        axg_negate = jnp.asarray(tensors.axg_negate)
        axg_klass = jnp.asarray(tensors.axg_klass.astype(np.int32))
        axg_rule = jnp.asarray(tensors.axg_rule)
        axg_any = jnp.asarray(tensors.axg_any)
        axg_filt = jnp.asarray(tensors.axg_filt)
        axf_rule = jnp.asarray(tensors.axf_rule)
        axf_is_ex = jnp.asarray(tensors.axf_is_exclude)

    def evaluate(mask, slot_valid, null_break, type_tag, str_id, num_hi,
                 num_lo, num_ok, num_plain, num_int, dur_hi, dur_lo, dur_ok,
                 dur_any, bool_val, elem0, kind_id, host_flag, live,
                 str_bytes, str_len, str_has_glob):
        B = mask.shape[0]
        C = c_path.shape[0]
        E = mask.shape[2]

        # ---- stage 1: string dictionary vs glob patterns
        match_nv = glob_match_matrix(nfa_char, nfa_star, nfa_q, nfa_len,
                                     str_bytes, str_len)
        empty_str = str_len == 0                              # for IS_NULL

        if C:
            # ---- stage 2: gather slots per check  [B, C, E]
            def g(x):
                return jnp.take(x, c_path, axis=1)

            mask_c = g(mask).astype(jnp.int32)
            valid_c = g(slot_valid)
            nbrk_c = g(null_break)
            type_c = g(type_tag).astype(jnp.int32)
            sid_c = g(str_id)
            numh_c = g(num_hi)
            numl_c = g(num_lo)
            numok_c = g(num_ok)
            nplain_c = g(num_plain)
            nint_c = g(num_int)
            bool_c = g(bool_val)
            elem0_c = g(elem0)

            # chain analysis per slot: bits 1..plen must be present; the FIRST
            # absent bit decides the outcome (fail, or pass when that depth is
            # equality-guarded; leaf depth is an implicit guard for ABSENT)
            leaf_bit = (1 << c_plen)[None, :, None]
            want_bits = (leaf_bit << 1) - 2
            absent_bits = (~mask_c) & want_bits
            first_absent = absent_bits & (-absent_bits)
            leaf_present = absent_bits == 0
            guard_pass = (first_absent & c_guard[None, :, None]) != 0

            # string match: gather by dictionary id (id -1 -> no string form)
            has_sid = sid_c >= 0
            str_hit = match_nv[c_nfa[None, :, None], jnp.maximum(sid_c, 0)] & has_sid & c_has_nfa[None, :, None]
            # value stringification exists only for str/bool/num leaves
            stringy = (type_c == T_STR) | (type_c == T_BOOL) | (type_c == T_NUM)

            # a nil value — explicit null leaf, or a cleanly missing key
            # (NOT a null-break, which is a structural FAIL) — converts to
            # "0" for quantity comparison (validate/common.go:9
            # convertNumberToString(nil)) and satisfies a null pattern
            # (validateValueWithNilPattern); the flattener leaves the num
            # lanes zeroed for exactly these slots
            nil_like = (type_c == T_NULL) | (~leaf_present & ~nbrk_c)
            numok_n = numok_c | nil_like

            lo_h, lo_l = c_lo_h[None, :, None], c_lo_l[None, :, None]
            hi_h, hi_l = c_hi_h[None, :, None], c_hi_l[None, :, None]
            ge_lo = ~_lex_lt(numh_c, numl_c, lo_h, lo_l)
            le_hi = ~_lex_lt(hi_h, hi_l, numh_c, numl_c)
            gt_lo = _lex_lt(lo_h, lo_l, numh_c, numl_c)
            lt_lo = _lex_lt(numh_c, numl_c, lo_h, lo_l)
            eq_lo = _lex_eq(numh_c, numl_c, lo_h, lo_l)
            in_range = ge_lo & le_hi

            # NUM_EQ literal semantics (pattern.go:67 int / :95 float):
            # string values must ParseInt / ParseFloat — quantity-only
            # strings ("250m") fail even when the micro values match
            mode = c_nummode[None, :, None]
            numk_v = type_c == T_NUM
            strk_v = type_c == T_STR
            lit_str_ok = jnp.where(mode == 1, nint_c, nplain_c)
            num_lit_ok = numok_c & (numk_v | (strk_v & lit_str_ok))

            # numfb string-op rows compare quantities on both sides
            # (validateNumberWithStr); nil converts to "0"
            numfb = c_numfb[None, :, None]
            num_eq = numok_n & eq_lo
            str_eq_ok = jnp.where(numfb, num_eq, stringy & str_hit)
            str_ne_ok = jnp.where(numfb, numok_n & ~eq_lo,
                                  stringy & ~str_hit)

            op = c_op[None, :, None]
            value_ok = jnp.select(
                [
                    op == CheckOp.STR_EQ,
                    op == CheckOp.STR_NE,
                    op == CheckOp.NUM_EQ,
                    op == CheckOp.NUM_NE,
                    op == CheckOp.NUM_GT,
                    op == CheckOp.NUM_GE,
                    op == CheckOp.NUM_LT,
                    op == CheckOp.NUM_LE,
                    op == CheckOp.NUM_IN_RANGE,
                    op == CheckOp.NUM_NOT_IN_RANGE,
                    op == CheckOp.BOOL_EQ,
                    op == CheckOp.IS_NULL,
                    op == CheckOp.EXISTS_OBJECT,
                    op == CheckOp.EXISTS_NONNIL,
                    op == CheckOp.EXISTS_LIST,
                    op == CheckOp.ABSENT,
                ],
                [
                    str_eq_ok,
                    str_ne_ok,
                    num_lit_ok & eq_lo,
                    num_lit_ok & ~eq_lo,
                    numok_n & gt_lo,
                    numok_n & ge_lo,
                    numok_n & lt_lo,
                    numok_n & ~gt_lo,
                    numok_n & in_range,
                    numok_n & ~in_range,
                    (type_c == T_BOOL) & (bool_c == c_bool[None, :, None]),
                    nil_like
                    | ((type_c == T_BOOL) & ~bool_c)
                    | ((type_c == T_NUM) & numok_c
                       & (numh_c == 0) & (numl_c == 0))
                    | ((type_c == T_STR) & empty_str[jnp.maximum(sid_c, 0)] & has_sid),
                    type_c == T_OBJ,
                    leaf_present & (type_c != T_NULL),
                    type_c == T_LIST,
                    jnp.ones_like(leaf_present),  # handled below
                ],
                default=jnp.zeros_like(leaf_present),
            )

            # a null-broken chain (the walk hit an existing non-map where
            # the pattern has a map) is a plain type-mismatch FAIL in the
            # oracle (validateResourceElement dispatch) — it must not be
            # rescued by guard bits or satisfy an absence anchor
            absent_ok = ~leaf_present & ~nbrk_c & (
                (first_absent & (c_guard[None, :, None] | leaf_bit)) != 0
            )
            # ops that evaluate a nil value instead of failing on absence:
            # the quantity family (nil -> "0" via validateNumberWithStr),
            # null patterns, and numfb string ops. NUM_EQ/NUM_NE literals
            # do NOT: validateValueWithIntPattern(nil) is plain false
            eval_on_nil = (
                ((op >= CheckOp.NUM_GT) & (op <= CheckOp.NUM_NOT_IN_RANGE))
                | (op == CheckOp.IS_NULL)
                | (((op == CheckOp.STR_EQ) | (op == CheckOp.STR_NE)) & numfb)
            )
            # nil evaluation applies only when every ancestor was walked
            # and the LEAF key itself is cleanly missing; a guarded level
            # (equality anchor) takes the absence-passes branch instead
            nil_leaf = (~leaf_present & ~nbrk_c & ~guard_pass
                        & (first_absent == leaf_bit))
            slot_ok = jnp.where(
                op == CheckOp.ABSENT,
                absent_ok,
                jnp.where(leaf_present | (nil_leaf & eval_on_nil),
                          value_ok, guard_pass & ~nbrk_c),
            )

            # ---- gates: per-element condition anchors in lists.
            # Two-level reduction: compound-alternative rows OR within
            # their group, predicate groups AND within the gate. Rows of a
            # group share one path, so slot validity is uniform; an
            # invalid slot keeps the gate neutrally open.
            gate_row_open = ~leaf_present | value_ok              # absent key opens

            def flat(x):
                return x.swapaxes(0, 1).reshape(C, B * E)

            gate_gseg = jnp.where(c_is_gate, c_group, n_groups)
            ggrp_open = _segment_or(
                jnp.where(c_is_gate[:, None],
                          flat(gate_row_open | ~valid_c), False),
                gate_gseg, n_groups + 1)[:n_groups]                # [G, B*E]
            gate_open = _segment_and(
                jnp.where(group_is_gate[:, None], ggrp_open, True),
                group_gate_seg, n_gates + 1
            )[:n_gates].reshape(n_gates, B, E)

            # gather gate state for gated checks by top-level element index
            has_gate = c_gate >= 0
            gate_idx = jnp.maximum(c_gate, 0)
            e0 = jnp.clip(elem0_c, 0, E - 1)
            gate_for_slot = gate_open[gate_idx[None, :, None],
                                      jnp.arange(B)[:, None, None], e0]
            gate_skips = has_gate[None, :, None] & (elem0_c >= 0) & ~gate_for_slot

            slot_ok = jnp.where(gate_skips, True, slot_ok)

            # ---- stage 3: element reduction
            and_ok = (slot_ok | ~valid_c).all(axis=2)
            or_ok = (slot_ok & valid_c & leaf_present).any(axis=2)
            # existence anchors: a missing anchored key silently passes
            # (the handler returns before validating); an empty list — key
            # present, zero slots — still fails the at-least-one check
            tr0 = c_track[None, :, None]
            # silent pass ONLY when the walk cleanly reached the parent map
            # and the anchored key itself is missing; a null-broken chain
            # or a missing ancestor is a structural FAIL before the
            # existence handler runs
            # ...or an equality-guarded ancestor is cleanly absent: the
            # =() anchor makes the whole subtree (existence included)
            # vacuous, same rescue as plain rows
            exist_clean_miss = ((first_absent == (1 << jnp.maximum(tr0, 0)))
                                | guard_pass) & ~nbrk_c
            exist_absent_ok = ((exist_clean_miss | ~valid_c).all(axis=2)
                               & valid_c.any(axis=2))
            check_ok = jnp.where(c_exist[None, :],
                                 or_ok | exist_absent_ok, and_ok)   # [B, C]

            # condition rows: key present & predicate failed -> skip; an
            # absent ANCESTOR of the key is a plain pattern failure (the
            # walk never reaches the anchor), not a skip. A compound
            # predicate fails only when EVERY alternative row of its group
            # misses, so the ok-OR reduces rows -> group first.
            cond_bit = (1 << jnp.maximum(c_cond_depth, 0))[None, :, None]
            cond_key_present = (mask_c & cond_bit) != 0
            cond_gseg = jnp.where(c_is_cond, c_group, n_groups)
            cgrp_ok = _segment_or(
                jnp.where(c_is_cond[:, None],
                          flat(leaf_present & value_ok), False),
                cond_gseg, n_groups + 1)[:n_groups]
            cgrp_kp = _segment_or(
                jnp.where(c_is_cond[:, None],
                          flat(cond_key_present & valid_c), False),
                cond_gseg, n_groups + 1)[:n_groups]
            cond_fail_g = (cgrp_kp & ~cgrp_ok).reshape(
                n_groups, B, E).any(axis=2)                        # [G, B]
            # chain failures: a cleanly absent ANCESTOR, or a null-break AT
            # the anchored key's level — the parent of the anchor exists
            # but is not a map, a structural FAIL the reference raises
            # before the anchor handler runs. An equality-GUARDED absent
            # ancestor is NOT a failure: =(key) absence makes the whole
            # subtree vacuous, so an anchor nested under it is never
            # reached (fuzz seed 70: {=(mode): {<(g): ...}} with mode
            # absent must pass, not fail)
            # ...but the guard rescues only a CLEANLY absent key: a chain
            # that null-breaks at the guarded depth means its parent
            # exists as a scalar/list — a structural type-mismatch FAIL
            # in the reference, same convention as absent_ok/nil_leaf
            cond_chain_fail_slot = (
                ((first_absent != 0) & (first_absent < cond_bit)
                 & ~(guard_pass & ~nbrk_c) & valid_c)
                | (nbrk_c & (first_absent == cond_bit) & valid_c))
            cond_chain_g = _segment_or(
                jnp.where(c_is_cond[:, None],
                          flat(cond_chain_fail_slot), False),
                cond_gseg, n_groups + 1)[:n_groups].reshape(
                n_groups, B, E).any(axis=2)                        # [G, B]

            # anchorMap tracking: tracked key never present while its parent was
            # validated -> fail becomes error (common/anchorKey.go:94). The
            # anchor registers only when the walk ENTERS the parent as a map:
            # a chain that null-breaks at the tracked depth means the parent
            # exists but is a scalar/list — validateMap never ran there, so
            # the oracle reports a plain type-mismatch FAIL, not an error
            tr = c_track[None, :, None]
            tr_parent = (mask_c >> jnp.maximum(tr - 1, 0)) & 1 > 0
            tr_present = (mask_c >> jnp.maximum(tr, 0)) & 1 > 0
            break_at_tr = nbrk_c & (first_absent == (1 << jnp.maximum(tr, 0)))
            registered = ((c_track[None, :] >= 0)
                          & (tr_parent & valid_c & ~break_at_tr).any(axis=2))
            anchor_missing = registered & ~(tr_present & valid_c).any(axis=2)

            # ---- stage 4: group / alt / rule reduction  (work in [C, B])
            # rows OR within a group ("a | b" compound alternatives,
            # pattern.go:153), groups AND within an alternative; a group
            # with no plain rows (gate/cond masks only) never constrains
            seg_ok = check_ok.T
            is_plain = ~(c_is_gate | c_is_cond)
            plain_seg = jnp.where(is_plain, c_group, n_groups)
            group_or = _segment_or(jnp.where(is_plain[:, None], seg_ok, False),
                                   plain_seg, n_groups + 1)[:n_groups]  # [G, B]
            group_ok = group_or | ~has_plain[:, None]
            alt_ok = _segment_and(group_ok, group_alt, n_alts)            # [A, B]

            alt_skip = _segment_or(
                jnp.where(cond_group[:, None], cond_fail_g, False),
                group_alt, n_alts)
            alt_chain_fail = _segment_or(
                jnp.where(cond_group[:, None], cond_chain_g, False),
                group_alt, n_alts)
            alt_ok = alt_ok & ~alt_chain_fail

            track_seg = jnp.where(c_track >= 0, c_alt, n_alts)
            alt_missing = _segment_or(
                jnp.where((c_track >= 0)[:, None], anchor_missing.T, False),
                track_seg, n_alts + 1,
            )[:n_alts]

            # per-alt verdict. A conditional-anchor skip combined with a
            # failing plain group is ORDER-dependent in the reference
            # (validateMap stops at the first failing handler in pattern
            # key order) — single-pattern rules route that to the host
            # lane; anyPattern alternatives fold skips into failures
            # (validation.go:448-480), so they stay decisive
            ambig = alt_skip & ~alt_ok & ~alt_is_multi[:, None]
            # anchor-missing failures are ALSO order-dependent: the
            # reference registers an anchor only when the walk reaches its
            # map (anchorKey.go:107 CheckAnchorInResource), and an earlier
            # sibling mismatch aborts the walk first — whether the failure
            # reports FAIL or ERROR depends on pattern key order, so the
            # oracle decides
            alt_verdict = jnp.where(
                ambig, V_HOST,
                jnp.where(alt_skip, V_SKIP,
                          jnp.where(alt_ok, V_PASS,
                                    jnp.where(alt_missing, V_HOST, V_FAIL))))

            # single-pattern rules: verdict = the alt verdict.
            # anyPattern rules: any pass -> pass, else fail (skips/errors are
            # folded into the failure list, validation.go:448-480)
            alt_pass = alt_verdict == V_PASS
            rule_pass = _segment_or(alt_pass, alt_rule, n_rules)
            single_verdict = jax.ops.segment_max(
                jnp.where(alt_is_multi[:, None], 0, alt_verdict),
                alt_rule, num_segments=n_rules)
            multi = jax.ops.segment_max(alt_is_multi[:, None].astype(jnp.int32) *
                                        jnp.ones((n_alts, B), jnp.int32),
                                        alt_rule, num_segments=n_rules) > 0
            verdict = jnp.where(
                multi, jnp.where(rule_pass, V_PASS, V_FAIL), single_verdict
            ).T                                                    # [B, R]

            # cells the device cannot score faithfully -> host lane when the
            # verdict would be adverse:
            # - gate rows whose key is absent in some element reproduce the
            #   reference's first-failing-element anchorMap order dependency
            #   (validateArrayOfMaps stops at the first non-conditional error)
            # - list-valued leaves under scalar checks: the reference ANDs
            #   the scalar compare over the list's elements
            #   (validate.go:79-86), which the device cannot do for lists
            #   the path dictionary did not expand — empty lists pass
            #   vacuously there while the device scores a plain FAIL
            gate_key_absent = (c_is_gate[None, :] &
                               (~leaf_present & valid_c & (elem0_c >= 0)).any(axis=2))
            # a gate row whose chain null-broke (list pattern over a
            # non-list) is a structural FAIL the reference raises before
            # any anchor runs; the gate lattice would let it pass open
            gate_struct = (c_is_gate[None, :] &
                           (nbrk_c & valid_c).any(axis=2))
            is_value_check = ~((op == CheckOp.ABSENT)
                               | (op == CheckOp.EXISTS_OBJECT)
                               | (op == CheckOp.EXISTS_NONNIL)
                               | (op == CheckOp.EXISTS_LIST))[:, :, 0]
            list_leaf = (is_value_check &
                         ((type_c == T_LIST) & leaf_present & valid_c).any(axis=2))
            unc_rows = gate_key_absent | list_leaf
            rule_seg = jnp.asarray(tensors.chk_rule)
            rule_uncertain = _segment_or(
                unc_rows.T, rule_seg, n_rules + 1)[:n_rules].T     # [B, R]
            verdict = jnp.where(
                rule_uncertain & ((verdict == V_FAIL) | (verdict == V_ERROR)
                                  | (verdict == V_SKIP)),
                V_HOST, verdict)
            rule_struct = _segment_or(
                gate_struct.T, rule_seg, n_rules + 1)[:n_rules].T
            verdict = jnp.where(rule_struct, V_HOST, verdict)
        else:
            # no pattern check rows at all (e.g. a deny-only policy
            # set): rules with alts pass vacuously (an empty pattern
            # map matches everything); everything else is composed in
            # stage 6. Computed without empty-operand scatters, which
            # abort the TPU backend (libtpu scatter_emitter check).
            verdict = jnp.broadcast_to(
                jnp.where(covered[None, :], V_PASS, V_NOT_APPLICABLE),
                (B, n_rules)).astype(jnp.int32)

        # ---- stage 5: aux programs (match/exclude/preconditions/deny)
        if X:
            def gx(arr):
                # aux paths are wildcard-free -> exactly one slot (e=0)
                return jnp.take(arr, x_path, axis=1)[:, :, 0]

            maskx = gx(mask).astype(jnp.int32)
            typex = gx(type_tag).astype(jnp.int32)
            sidx = gx(str_id)
            nhx, nlx = gx(num_hi), gx(num_lo)
            nokx = gx(num_ok)
            nplainx = gx(num_plain)
            nintx = gx(num_int)
            dhx, dlx = gx(dur_hi), gx(dur_lo)
            durokx = gx(dur_ok)
            duranyx = gx(dur_any)
            boolx = gx(bool_val)
            nbrkx = gx(null_break)

            leafb = (1 << x_plen)[None, :]
            wantb = (leafb << 1) - 2
            presx = ((~maskx) & wantb) == 0
            # a chain broken at a non-map node resolves to null (not an
            # unresolved variable): conditions see a null key -> false,
            # while a missing map key is a true absence (precondition ""
            # substitute / deny substitution error)
            nullx = (presx & (typex == T_NULL)) | (~presx & nbrkx)
            absx = ~presx & ~nbrkx

            hasid = sidx >= 0
            sid0 = jnp.maximum(sidx, 0)
            globx = match_nv[x_nfa[None, :], sid0] & hasid & x_has_nfa[None, :]
            keyglob = str_has_glob[sid0] & hasid

            strk = typex == T_STR
            numk = typex == T_NUM
            boolk = typex == T_BOOL
            listk = typex == T_LIST

            qh, ql = x_q_h[None, :], x_q_l[None, :]
            sh, sl = x_s_h[None, :], x_s_l[None, :]
            n_lt_q = _lex_lt(nhx, nlx, qh, ql)
            n_gt_q = _lex_lt(qh, ql, nhx, nlx)
            n_eq_q = _lex_eq(nhx, nlx, qh, ql)
            n_lt_s = _lex_lt(nhx, nlx, sh, sl)
            n_gt_s = _lex_lt(sh, sl, nhx, nlx)
            d_lt_s = _lex_lt(dhx, dlx, sh, sl)
            d_gt_s = _lex_lt(sh, sl, dhx, dlx)
            d_eq_s = _lex_eq(dhx, dlx, sh, sl)

            o_str = x_o_str[None, :]
            o_num = x_o_num[None, :]
            o_dur = x_o_dur[None, :]
            o_float = x_o_float[None, :]
            o_int = x_o_int[None, :]
            o_quant = x_o_quant[None, :]

            # NOTE: these predicate trees are written in pure boolean
            # algebra (no nested jnp.where chains) — the TPU backend
            # miscompiles fused where-on-bool chains here (verified with
            # tests/manual_tpu_fusion_check.py); and/or/not lowers cleanly.

            # Equals (operator/equal.go; engine/operators._equal):
            #   bool key: operand must be bool and equal
            #   number key: micro-unit equality; a string operand must parse
            #     the way the key's Go type requires (Atoi for int keys,
            #     ParseFloat for float keys)
            #   string key: duration pair first, then quantity-vs-quantity,
            #   then the operand is the wildcard pattern over the key
            dur_pair = durokx & (o_dur | o_num)       # string-key dur pair
            ceq = (
                (boolk & x_o_bool[None, :] & (boolx == x_obool[None, :]))
                | (numk & nokx & o_quant & n_eq_q
                   & (o_num | (o_str & ((nintx & o_int)
                                        | (~nintx & o_float)))))
                | (strk & ((dur_pair & d_eq_s)
                           | (~dur_pair & nokx & o_str & o_quant & n_eq_q)
                           | (~dur_pair & ~nokx & o_str & globx)))
            )

            def rel4(base, lt, gt):
                opx_ = x_op[None, :]
                return (((opx_ == base) & gt)
                        | ((opx_ == base + 1) & ~lt)
                        | ((opx_ == base + 2) & lt)
                        | ((opx_ == base + 3) & ~gt))

            cmp_q = rel4(int(AuxOp.CGT), n_lt_q, n_gt_q)
            cmp_ns = rel4(int(AuxOp.CGT), n_lt_s, n_gt_s)
            cmp_ds = rel4(int(AuxOp.CGT), d_lt_s, d_gt_s)
            # GreaterThan family (variables/operator/numeric.go): duration
            # pair, then float key, then quantity-vs-quantity-string
            numkey_cmp = ((o_num & cmp_q)
                          | (~o_num & o_str & o_dur & cmp_ns)
                          | (~o_num & o_str & ~o_dur & o_float & cmp_q))
            cnum = (
                (numk & numkey_cmp)
                | (strk & dur_pair & cmp_ds)
                | (strk & ~dur_pair & nplainx & numkey_cmp)
                | (strk & ~dur_pair & ~nplainx & nokx
                   & o_str & o_quant & cmp_q)
            )
            # Duration* family (variables/operator/duration.go): both sides
            # as seconds; numbers are seconds, strings must Go-parse
            dnum = rel4(int(AuxOp.DGT), n_lt_s, n_gt_s)
            ddur = rel4(int(AuxOp.DGT), d_lt_s, d_gt_s)
            cdur = (numk & dnum) | (strk & duranyx & ddur)

            # In-family rows: the NFA row is literal(item) for CIN_ITEM
            # (in.go:62 keyExistsInArray — the key is the wildcard pattern,
            # exact on device, host lane for metachar keys) and
            # glob(value) for CIN_GLOB
            in_keyish = strk | (numk & x_allow_num[None, :] & nintx)
            cin = in_keyish & globx

            opx = x_op[None, :]
            op_val = (
                ((opx == int(AuxOp.TRUE)))
                | ((opx == int(AuxOp.GLOB)) & (strk | (numk & nintx)) & globx)
                | ((opx == int(AuxOp.EXISTS)) & presx)
                | ((opx == int(AuxOp.NOT_EXISTS)) & ~presx)
                | ((opx == int(AuxOp.CEQ)) & ceq)
                | (((opx == int(AuxOp.CIN_ITEM))
                    | (opx == int(AuxOp.CIN_GLOB))) & cin)
                | ((opx >= int(AuxOp.CGT)) & (opx <= int(AuxOp.CLE)) & cnum)
                | ((opx >= int(AuxOp.DGT)) & (opx <= int(AuxOp.DLE)) & cdur)
            )

            # absence semantics differ by row class: match/exclude rows
            # treat null like absent (utils.go reads fields with or-"");
            # PRECONDITION rows fold null into the ""-substitution result
            # (the vars.go:62-74 resolver maps both to ""), while DENY rows
            # treat null as false here — the substitution-error path
            # (errx below) turns those cells into rule ERROR
            absres = x_absent[None, :]
            is_exist_op = ((opx == int(AuxOp.EXISTS))
                           | (opx == int(AuxOp.NOT_EXISTS)))
            pres_nonnull = presx & (typex != T_NULL)
            match_val = ((is_exist_op & op_val)
                         | (~is_exist_op & pres_nonnull & op_val)
                         | (~is_exist_op & ~pres_nonnull & absres))
            x_deny_row = jnp.asarray(ax_klass_np == AUX_DENY)[None, :]
            cond_val_deny = ~nullx & ((presx & op_val) | (~presx & absres))
            cond_val_pre = ((presx & ~nullx & op_val)
                            | ((~presx | nullx) & absres))
            cond_val = jnp.where(x_deny_row, cond_val_deny, cond_val_pre)
            is_mk = x_is_match_klass[None, :]
            has_p = x_has_path[None, :]
            rowv = (is_mk & match_val) | (~is_mk & cond_val)
            rowv = (has_p & rowv) | (~has_p & op_val)
            kind_ok = (x_kind[None, :] < 0) | (kind_id[:, None] == x_kind[None, :])
            rowv = rowv & kind_ok
            # FUSION FENCE — the TPU backend miscompiles the aux predicate
            # tree when it fuses into the segment reductions (wrong deny /
            # precondition verdicts; reproduced deterministically, see
            # tests/manual_tpu_fusion_check.py). Materializing the [B, X]
            # row values here keeps the bad fusion from forming; the cost
            # is one small boolean tensor per batch.
            rowv = jax.lax.optimization_barrier(rowv)

            # rows the device cannot score faithfully -> host lane:
            # list-valued keys (set-containment, in.go:110), float keys in
            # In rows (fmt.Sprint formatting differs from the equality
            # interning), metachar keys acting as patterns, non-stringy
            # values under a match glob. A kind-gated row that missed its
            # kind is definitively false, never uncertain. Match-row and
            # condition-row uncertainty compose differently in stage 6: a
            # certain match miss makes condition uncertainty irrelevant.
            is_cinop = (opx == int(AuxOp.CIN_ITEM)) | (opx == int(AuxOp.CIN_GLOB))
            # invalid key types map to constant false PRE-negation in the
            # reference (in.go invalid-type handling); the XOR-negate group
            # lattice cannot express that, so negated groups with such keys
            # take the host lane (un-negated groups already evaluate false)
            xg_negated = axg_negate[x_group][None, :]
            unc = is_cinop & (
                listk
                | (typex == T_OBJ)
                | (xg_negated & boolk)
                | (numk & x_allow_num[None, :] & ~nintx)
                | (x_key_pat[None, :] & strk & keyglob))
            unc = unc | ((opx == int(AuxOp.GLOB)) & presx
                         & ~(strk | (numk & nintx) | (typex == T_NULL)))
            unc = unc & kind_ok
            unc_m = unc & is_mk
            unc_c = unc & ~is_mk
            match_unc = _segment_or(unc_m.T, x_rule, n_rules).T    # [B, R]
            cond_unc = _segment_or(unc_c.T, x_rule, n_rules).T     # [B, R]

            # deny rows whose key is a missing map key OR resolves to
            # null: the reference's substitution fails in both cases ->
            # rule ERROR (validation.go:299 validateDeny; vars.go treats
            # a nil resolution like NotFoundVariableErr)
            errx = x_err[None, :] & (absx | nullx) & x_has_path[None, :]
            deny_err = _segment_or(errx.T, x_rule, n_rules).T      # [B, R]

            # group OR -> XOR negate
            grp0 = _segment_or(rowv.T, x_group, n_axg)
            neg = axg_negate[:, None]
            grp = (neg & ~grp0) | (~neg & grp0)

            # match/exclude: groups AND within a filter
            has_filt = axg_filt >= 0
            filt_seg = jnp.where(has_filt, axg_filt, n_axf)
            filt_ok = _segment_and(
                ~has_filt[:, None] | grp, filt_seg, n_axf + 1
            )[:n_axf]                                              # [FX, B]

            # filters -> rule: match.any = OR, match.all / single = AND;
            # exclude.any = OR, exclude.all = AND (utils.go:265-337)
            is_m = ~axf_is_ex
            mseg = jnp.where(is_m, axf_rule, n_rules)
            m_or = _segment_or(is_m[:, None] & filt_ok,
                               mseg, n_rules + 1)[:n_rules]
            m_and = _segment_and(~is_m[:, None] | filt_ok,
                                 mseg, n_rules + 1)[:n_rules]
            m_any = rule_match_any[:, None]
            match_ok = (m_any & m_or) | (~m_any & m_and)
            match_ok = match_ok | ~rule_has_match[:, None]
            eseg = jnp.where(axf_is_ex, axf_rule, n_rules)
            e_or = _segment_or(axf_is_ex[:, None] & filt_ok,
                               eseg, n_rules + 1)[:n_rules]
            e_and = _segment_and(~axf_is_ex[:, None] | filt_ok,
                                 eseg, n_rules + 1)[:n_rules]
            e_all = rule_exclude_all[:, None]
            exclude_hit = (((e_all & e_and) | (~e_all & e_or))
                           & rule_has_exclude[:, None])
            applicable_aux = (match_ok & ~exclude_hit).T           # [B, R]

            # conditions: AND(all-block) AND (OR(any-block) if any present)
            # (variables/evaluate.go:21 evaluateAnyAllConditions)
            def cond_reduce(klass_const, has_any_col):
                isk = axg_klass == klass_const
                in_all = isk & ~axg_any
                in_any = isk & axg_any
                all_seg = jnp.where(in_all, axg_rule, n_rules)
                all_ok = _segment_and(
                    ~in_all[:, None] | grp, all_seg,
                    n_rules + 1)[:n_rules]
                any_seg = jnp.where(in_any, axg_rule, n_rules)
                any_ok = _segment_or(
                    in_any[:, None] & grp, any_seg,
                    n_rules + 1)[:n_rules]
                return (all_ok & (any_ok | ~has_any_col[:, None])).T

            precond_ok = cond_reduce(AUX_PRECOND, rule_precond_any)
            deny_match = cond_reduce(AUX_DENY, rule_deny_any)
        else:
            applicable_aux = jnp.ones((B, n_rules), bool)
            precond_ok = jnp.ones((B, n_rules), bool)
            deny_match = jnp.zeros((B, n_rules), bool)
            deny_err = jnp.zeros((B, n_rules), bool)
            match_unc = jnp.zeros((B, n_rules), bool)
            cond_unc = jnp.zeros((B, n_rules), bool)

        # ---- stage 6: verdict composition
        deny_v = jnp.where(deny_err, V_ERROR,
                           jnp.where(deny_match, V_FAIL, V_PASS))
        verdict = jnp.where(rule_deny[None, :], deny_v, verdict)

        # pattern rules with no device rows at all (host-only handled below)
        verdict = jnp.where((~covered & ~rule_host & ~rule_deny)[None, :],
                            V_NOT_APPLICABLE, verdict)

        # failed preconditions -> SKIP; uncertain condition rows -> HOST;
        # then a CERTAIN match miss / exclude hit -> NOT_APPLICABLE (a
        # non-matching rule produces no rule response, making condition
        # uncertainty irrelevant); finally uncertain match rows -> HOST
        # (the applicability determination itself is unreliable)
        verdict = jnp.where(precond_ok, verdict, V_SKIP)
        verdict = jnp.where(cond_unc & ~rule_host[None, :], V_HOST, verdict)
        verdict = jnp.where(applicable_aux | rule_host[None, :],
                            verdict, V_NOT_APPLICABLE)
        verdict = jnp.where(match_unc & ~rule_host[None, :], V_HOST, verdict)

        verdict = jnp.where(rule_host[None, :], V_HOST, verdict)
        # legacy kind prefilter gates host-lane rules only (device rules
        # carry their full match program as aux rows)
        kind_hit = (rule_kind_ids[None, :, :] == kind_id[:, None, None]).any(-1)
        applicable_host = kind_hit | rule_all_kinds[None, :]
        verdict = jnp.where(rule_host[None, :] & ~applicable_host,
                            V_NOT_APPLICABLE, verdict)

        # resources flagged by the flattener take the host lane entirely
        # (their aux program may be unreliable too, so HOST overrides NA)
        verdict = jnp.where(host_flag[:, None], V_HOST, verdict)
        # mesh-pad rows -> NOT_APPLICABLE (explicit flag: a real resource
        # may have zero valid slots when every path crosses an empty array)
        verdict = jnp.where(live[:, None], verdict, V_NOT_APPLICABLE)
        if _DEBUG is not None and X:
            return verdict.astype(jnp.int8), dict(
                presx=presx, globx=globx, op_val=op_val, rowv=rowv, grp=grp,
                deny_match=deny_match, precond_ok=precond_ok,
                match_ok=match_ok, applicable_aux=applicable_aux, ceq=ceq,
                deny_err=deny_err, match_unc=match_unc, cond_unc=cond_unc)
        return verdict.astype(jnp.int8)

    return jax.jit(evaluate) if jit else evaluate


def build_eval_fn_packed(tensors: PolicyTensors, jit: bool = True):
    """Packed-transfer variant of :func:`build_eval_fn`: takes
    (cells, bmeta, str_bytes, dictv) — see flatten.PACKED_BATCH_ARRAYS —
    and unpacks the 22 evaluation lanes on device (bit ops + dictionary
    gathers that XLA fuses into the kernel). Cuts H2D to ~8 bytes/cell
    over 4 arrays, which dominates e2e rate on tunnel-attached chips."""
    from ..models.flatten import unpack_batch

    base = build_eval_fn(tensors, jit=False)

    def evaluate_packed(cells, bmeta, str_bytes, dictv):
        return base(*unpack_batch(cells, bmeta, str_bytes, dictv, xp=jnp))

    return jax.jit(evaluate_packed) if jit else evaluate_packed


def build_eval_fn_live(tensors: PolicyTensors, jit: bool = True):
    """Shard-local eval geometry: :func:`build_eval_fn_packed` with the
    verdict sliced to the live rule prefix *on device*. A policy shard's
    rule axis pads to a power-of-two bucket (assemble_tensors
    rule_bucket); with P shards in flight the inert columns would
    otherwise transfer P times per chunk, so the 2D mesh path
    (parallel/mesh.py) slices them off before the gather. The batch's
    path axis may be wider than this tensor set's dictionary snapshot —
    ids are append-only-global, every gather stays in bounds."""
    from ..models.flatten import unpack_batch

    base = build_eval_fn(tensors, jit=False)
    live = tensors.n_rules_live

    def evaluate_live(cells, bmeta, str_bytes, dictv):
        v = base(*unpack_batch(cells, bmeta, str_bytes, dictv, xp=jnp))
        return v[:, :live]

    return jax.jit(evaluate_live) if jit else evaluate_live


def _split_blob(blob, B: int, P: int, E: int, V: int):
    """Slice one uint32 transfer buffer (FlatBatch.packed_blob) back into
    (cells, bmeta, str_bytes, dictv). The string bytes travel as uint32
    words; explicit little-endian shifts (not bitcast) keep the layout
    backend-independent."""
    from ..models.compiler import STR_LEN

    w = STR_LEN // 4          # uint32 words per dictionary string
    o0 = B * P * E * 2
    cells = blob[:o0].reshape(B, P, E, 2)
    bmeta = blob[o0:o0 + B]
    o1 = o0 + B
    dictv = blob[o1:o1 + V * 5].reshape(V, 5)
    o2 = o1 + V * 5
    sw = blob[o2:o2 + V * w].reshape(V, w)
    str_bytes = jnp.stack(
        [(sw >> s) & 0xFF for s in (0, 8, 16, 24)], axis=-1,
    ).reshape(V, STR_LEN).astype(jnp.uint8)
    return cells, bmeta, str_bytes, dictv


def build_eval_fn_blob(tensors: PolicyTensors, donate: bool = False):
    """Single-transfer variant: fn(blob, B, P, E, V) -> verdict [B, R].
    Shapes are static jit arguments (one compile per chunk geometry).

    ``donate=True`` marks the blob argument donated (donate_argnums):
    on a warm stable-shape bucket XLA may alias the input transfer
    buffer into the kernel's workspace instead of copying it — the
    steady-state zero-copy leg of the streaming plane. Callers must
    device_put the blob themselves and treat the device array as
    consumed after the call (engine.evaluate_device_async does both)."""
    from functools import partial

    from ..models.flatten import unpack_batch

    base = build_eval_fn(tensors, jit=False)

    @partial(jax.jit, static_argnums=(1, 2, 3, 4),
             donate_argnums=(0,) if donate else ())
    def evaluate_blob(blob, B, P, E, V):
        parts = _split_blob(blob, B, P, E, V)
        return base(*unpack_batch(*parts, xp=jnp))

    return evaluate_blob


def build_scan_fn_blob(tensors: PolicyTensors):
    """Background-scan kernel: fn(blob, B, P, E, V) ->
    (fail_counts [R] i32, pass_counts [R] i32, host_rows [B] bool).
    The per-rule counts reduce on device so the scan reads back ~bytes,
    not the [B, R] verdict matrix — the D2H round trip was a fifth of the
    1M-scan wall time (BENCH_r03 config 5)."""
    from functools import partial

    from ..models.flatten import unpack_batch

    base = build_eval_fn(tensors, jit=False)

    @partial(jax.jit, static_argnums=(1, 2, 3, 4))
    def scan_blob(blob, B, P, E, V):
        parts = _split_blob(blob, B, P, E, V)
        v = base(*unpack_batch(*parts, xp=jnp))
        host_rows = (v == V_HOST).any(axis=1)
        # counts cover NON-host rows only: a flagged row resolves through
        # the CPU oracle wholesale (scan callers add its counts from the
        # oracle verdicts), so splitting by row keeps the accounting
        # exact without reading back per-cell HOST masks
        live = ~host_rows[:, None]
        fails = ((v == V_FAIL) & live).sum(axis=0, dtype=jnp.int32)
        passes = ((v == V_PASS) & live).sum(axis=0, dtype=jnp.int32)
        return fails, passes, host_rows

    return scan_blob
