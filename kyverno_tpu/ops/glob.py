"""Vectorized glob (wildcard) matching as an NFA bitmask simulation.

The reference's leaf comparator calls minio/pkg/wildcard.Match per
(pattern, value) pair inside the recursive tree walk
(/root/reference/pkg/engine/validate/pattern.go:210). Here the whole
pattern-set x string-dictionary product is computed in one shot:

    match[n, v] = glob(pattern_n) accepts string_v

The NFA has one state per pattern position; a boolean state vector steps
through the value's bytes under ``lax.scan``. ``*`` states self-loop and
epsilon-advance (consecutive stars are collapsed at compile time, so one
propagation step per transition suffices). Everything is elementwise
boolean math over a [N, V, S] lattice — ideal VPU work, no MXU needed, no
data-dependent shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _epsilon_closure(states, is_star_pad):
    """Advance through '*' states without consuming input. Star runs are
    collapsed at compile time, so a single shift suffices."""
    advanced = jnp.pad(states[..., :-1] & is_star_pad[..., None, :-1], ((0, 0), (0, 0), (1, 0)))
    return states | advanced


def glob_match_matrix(nfa_char, nfa_is_star, nfa_is_q, nfa_len, str_bytes, str_len):
    """match[n, v] for every (glob pattern n, dictionary string v).

    Args (device arrays):
      nfa_char:    [N, S] uint8 literal byte per state (0 for meta states)
      nfa_is_star: [N, S] bool
      nfa_is_q:    [N, S] bool
      nfa_len:     [N]    int32 pattern length (accepting state index)
      str_bytes:   [V, L] uint8 zero-padded string bytes
      str_len:     [V]    int32
    Returns: [N, V] bool
    """
    nfa_char, nfa_is_star, nfa_is_q, nfa_len, str_bytes, str_len = (
        jnp.asarray(a) for a in
        (nfa_char, nfa_is_star, nfa_is_q, nfa_len, str_bytes, str_len)
    )
    n, s = nfa_char.shape
    v, l = str_bytes.shape

    init = jnp.zeros((n, v, s + 1), dtype=bool).at[:, :, 0].set(True)
    star_pad = jnp.pad(nfa_is_star, ((0, 0), (0, 1)))
    q_pad = jnp.pad(nfa_is_q, ((0, 0), (0, 1)))
    char_pad = jnp.pad(nfa_char, ((0, 0), (0, 1)))
    init = _epsilon_closure(init, star_pad)

    def step(states, j):
        c = str_bytes[:, j]                                   # [V]
        in_range = j < str_len                                # [V]
        # consume c: state i -> i+1 when pattern[i] is '?' or == c
        consume = q_pad[:, None, :] | (char_pad[:, None, :] == c[None, :, None])
        advanced = jnp.pad((states & consume)[..., :-1], ((0, 0), (0, 0), (1, 0)))
        # '*' consumes c staying in place
        stay = states & star_pad[:, None, :]
        new = _epsilon_closure(advanced | stay, star_pad)
        states = jnp.where(in_range[None, :, None], new, states)
        return states, None

    states, _ = jax.lax.scan(step, init, jnp.arange(l))
    return jnp.take_along_axis(
        states, nfa_len[:, None, None].astype(jnp.int32), axis=2
    )[:, :, 0]
