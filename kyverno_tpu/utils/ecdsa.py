"""Minimal ECDSA P-256 (secp256r1) for cosign signature envelopes.

The reference links the cosign/sigstore crypto stack
(/root/reference/pkg/cosign/cosign.go); the deployable subset it actually
exercises for key-based verification is "ECDSA-P256-SHA256 over a payload
blob, DER-encoded signature, SPKI PEM public key". That fits in one
dependency-free module: point arithmetic on P-256, SHA-256 via hashlib,
DER/PEM codecs. Signing exists for tests and the CLI's local trust store;
verification is the production path. Performance is irrelevant here —
admission verifies a handful of signatures per request, each ~1ms.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import secrets

# ------------------------------------------------------------ curve P-256

P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
A = P - 3
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5


def _inv(x: int, m: int) -> int:
    return pow(x, -1, m)


def _add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1 + A) * _inv(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv((x2 - x1) % P, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


def _mul(k: int, point):
    out = None
    addend = point
    while k:
        if k & 1:
            out = _add(out, addend)
        addend = _add(addend, addend)
        k >>= 1
    return out


def on_curve(point) -> bool:
    if point is None:
        return False
    x, y = point
    return (y * y - (x * x * x + A * x + B)) % P == 0


# ---------------------------------------------------------------- DER/PEM


def _der_len(buf: bytes, i: int) -> tuple[int, int]:
    first = buf[i]
    i += 1
    if first < 0x80:
        return first, i
    n = first & 0x7F
    return int.from_bytes(buf[i:i + n], "big"), i + n


def der_decode_signature(sig: bytes) -> tuple[int, int]:
    """SEQUENCE { INTEGER r, INTEGER s } -> (r, s)."""
    if not sig or sig[0] != 0x30:
        raise ValueError("bad DER signature")
    _, i = _der_len(sig, 1)
    out = []
    for _ in range(2):
        if sig[i] != 0x02:
            raise ValueError("bad DER integer")
        ln, i = _der_len(sig, i + 1)
        out.append(int.from_bytes(sig[i:i + ln], "big"))
        i += ln
    return out[0], out[1]


def der_encode_signature(r: int, s: int) -> bytes:
    def integer(v: int) -> bytes:
        body = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
        if body[0] & 0x80:
            body = b"\x00" + body
        return b"\x02" + bytes([len(body)]) + body

    body = integer(r) + integer(s)
    return b"\x30" + bytes([len(body)]) + body


_SPKI_EC_P256 = bytes.fromhex(
    # SEQUENCE { SEQUENCE { OID ecPublicKey, OID prime256v1 }, BIT STRING
    "3059301306072a8648ce3d020106082a8648ce3d030107034200"
)


def load_public_key_pem(pem: str) -> tuple[int, int]:
    """SPKI PEM -> curve point. Only uncompressed P-256 keys (what
    ``cosign generate-key-pair`` emits)."""
    body = "".join(
        line for line in pem.strip().splitlines()
        if not line.startswith("-----"))
    der = base64.b64decode(body)
    if not der.startswith(_SPKI_EC_P256) or len(der) < len(_SPKI_EC_P256) + 65:
        raise ValueError("unsupported public key (want SPKI ECDSA P-256)")
    raw = der[len(_SPKI_EC_P256):]
    if raw[0] != 0x04:
        raise ValueError("unsupported EC point encoding")
    point = (int.from_bytes(raw[1:33], "big"),
             int.from_bytes(raw[33:65], "big"))
    if not on_curve(point):
        raise ValueError("public key not on curve")
    return point


def public_key_to_pem(point: tuple[int, int]) -> str:
    raw = b"\x04" + point[0].to_bytes(32, "big") + point[1].to_bytes(32, "big")
    der = _SPKI_EC_P256 + raw
    b64 = base64.b64encode(der).decode()
    lines = [b64[i:i + 64] for i in range(0, len(b64), 64)]
    return ("-----BEGIN PUBLIC KEY-----\n"
            + "\n".join(lines) + "\n-----END PUBLIC KEY-----\n")


# ------------------------------------------------------------------ ECDSA


def generate_keypair() -> tuple[int, tuple[int, int]]:
    d = secrets.randbelow(N - 1) + 1
    return d, _mul(d, (GX, GY))


def _rfc6979_k(priv: int, digest: bytes) -> int:
    """Deterministic nonce (RFC 6979) — keeps test fixtures stable."""
    holen = 32
    x = priv.to_bytes(32, "big")
    h1 = digest
    v = b"\x01" * holen
    k = b"\x00" * holen
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(priv: int, message: bytes) -> bytes:
    """DER-encoded ECDSA-SHA256 signature (test/CLI signing path)."""
    digest = hashlib.sha256(message).digest()
    z = int.from_bytes(digest, "big")
    while True:
        k = _rfc6979_k(priv, digest)
        x, _ = _mul(k, (GX, GY))
        r = x % N
        if r == 0:
            continue
        s = _inv(k, N) * (z + r * priv) % N
        if s == 0:
            continue
        return der_encode_signature(r, s)


def verify(pub: tuple[int, int], message: bytes, der_sig: bytes) -> bool:
    """ECDSA-SHA256 verify; False on any malformed input."""
    try:
        r, s = der_decode_signature(der_sig)
    except (ValueError, IndexError):
        return False
    if not (1 <= r < N and 1 <= s < N) or not on_curve(pub):
        return False
    z = int.from_bytes(hashlib.sha256(message).digest(), "big")
    w = _inv(s, N)
    u1 = z * w % N
    u2 = r * w % N
    point = _add(_mul(u1, (GX, GY)), _mul(u2, pub))
    if point is None:
        return False
    return point[0] % N == r
