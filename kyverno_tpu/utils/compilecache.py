"""Persistent XLA compilation cache, on by default.

First compilation of each kernel shape costs ~20-40s against the
tunnel-attached chip; the policy engine's shape-bucketing keeps the
shape count small and stable, which makes a persistent cache unusually
effective — a controller restart (or a benchmark rerun) skips straight
to warm dispatch (measured: 10.5s -> 0.5s across processes on the axon
backend). The cache is content-addressed by program + compiler
fingerprint, so a mismatched backend simply misses and recompiles.

``KTPU_COMPILE_CACHE=0`` disables; ``KTPU_COMPILE_CACHE_DIR`` overrides
the location (default: ``.jax_compilation_cache/`` at the repo root,
gitignored).
"""

from __future__ import annotations

from pathlib import Path

from ..runtime import featureplane

_enabled = False


def enable() -> None:
    """Idempotent; called wherever jit functions are built (ops.eval
    import). Must run before heavy compilation, not before jax import."""
    global _enabled
    if _enabled or not featureplane.enabled("KTPU_COMPILE_CACHE"):
        return
    explicit = featureplane.raw("KTPU_COMPILE_CACHE_DIR") or None
    try:
        import jax

        # XLA:CPU AOT reloads warn about machine-feature mismatches (and
        # can SIGILL across hosts); CPU compiles are seconds anyway — the
        # 20-40s wins are all on the accelerator side. Checked against
        # the RESOLVED backend (env vars miss the no-accelerator
        # fallback); enable() is called from the jit builders, where
        # backend initialization is imminent regardless.
        if jax.default_backend() == "cpu":
            return
        path = explicit or str(
            Path(__file__).resolve().parents[2] / ".jax_compilation_cache")
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
    except Exception as e:
        # best-effort by default — but an EXPLICIT opt-in that can't take
        # effect must say so, or every restart silently pays full compiles
        if explicit:
            import logging

            logging.getLogger(__name__).warning(
                "KTPU_COMPILE_CACHE_DIR=%s set but the persistent "
                "compilation cache could not be enabled: %s", explicit, e)
