"""Kubernetes resource.Quantity parsing and comparison.

Reimplements the subset of k8s.io/apimachinery/pkg/api/resource used by the
leaf comparator (/root/reference/pkg/engine/validate/pattern.go:264-309):
parse a quantity string ("100Mi", "1500m", "2", "3e2", "1.5Gi") to an exact
rational and compare. Parsing is exact (fractions.Fraction), so "0.1" and
"100m" compare equal, as they do under k8s Quantity semantics.

The TPU compiler reuses :func:`decompose` to pre-split operands into
(mantissa, exponent) lanes so the on-device comparator is pure arithmetic.
"""

from __future__ import annotations

import re
from fractions import Fraction

_BINARY = {
    "Ki": Fraction(2**10),
    "Mi": Fraction(2**20),
    "Gi": Fraction(2**30),
    "Ti": Fraction(2**40),
    "Pi": Fraction(2**50),
    "Ei": Fraction(2**60),
}
_DECIMAL = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}

# number then suffix; scientific exponent must win over the bare E/e suffix
_QUANTITY_RE = re.compile(
    r"^([+-]?)(\d+(?:\.\d*)?|\.\d+)"
    r"(Ki|Mi|Gi|Ti|Pi|Ei|[eE][+-]?\d+|[numkMGTPE])?$"
)


class QuantityError(ValueError):
    pass


def parse_quantity(s: str) -> Fraction:
    """Parse a k8s quantity string into an exact Fraction.

    Raises QuantityError on anything unparseable (the caller treats that as
    "not a quantity, fall back to wildcard string match").
    """
    if not isinstance(s, str):
        raise QuantityError(f"not a string: {s!r}")
    s = s.strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise QuantityError(f"invalid quantity: {s!r}")
    sign, number, suffix = m.group(1), m.group(2), m.group(3) or ""
    if "." in number:
        whole, frac = number.split(".")
        base = Fraction(int(whole or "0")) + (
            Fraction(int(frac), 10 ** len(frac)) if frac else Fraction(0)
        )
    else:
        base = Fraction(int(number))
    if suffix in _BINARY:
        mult = _BINARY[suffix]
    elif suffix in _DECIMAL:
        mult = _DECIMAL[suffix]
    elif suffix[:1] in ("e", "E"):
        exp = int(suffix[1:])
        mult = Fraction(10) ** exp
    else:  # pragma: no cover - regex prevents this
        raise QuantityError(f"invalid suffix: {suffix!r}")
    value = base * mult
    return -value if sign == "-" else value


def compare_quantities(a: Fraction, b: Fraction) -> int:
    """Three-way compare: -1, 0, 1 (mirrors Quantity.Cmp)."""
    if a < b:
        return -1
    if a > b:
        return 1
    return 0


def is_quantity(s: str) -> bool:
    try:
        parse_quantity(s)
        return True
    except QuantityError:
        return False


def decompose(s: str) -> tuple[float, bool]:
    """(float value, ok) for the TPU operand lanes.

    float64 loses exactness for extreme quantities (> 2^53); acceptable for
    the accelerated tier because the CPU oracle is authoritative for ties —
    the compiler routes patterns whose operands exceed the exact-float range
    to the CPU lane.
    """
    try:
        q = parse_quantity(s)
    except QuantityError:
        return 0.0, False
    return float(q), True
