"""Glob wildcard matching: ``*`` (any run, incl. empty) and ``?`` (one char).

Semantics match the matcher used throughout the reference engine
(minio wildcard.Match, used from /root/reference/pkg/engine/validate/pattern.go:241
and the match/exclude filters). No character classes, no escapes.

This is the host-side scalar twin of the batched bitap kernel in
``kyverno_tpu.ops.bitap`` — both must agree on every (pattern, text) pair.
"""

from __future__ import annotations


def wildcard_match(pattern: str, text: str) -> bool:
    """Return True iff ``text`` matches glob ``pattern``.

    Two-pointer with star backtracking: O(len(p) * len(t)) worst case,
    O(len(t)) typical.
    """
    p, s = pattern, text
    pi = si = 0
    star = -1
    star_si = 0
    np_, ns = len(p), len(s)
    while si < ns:
        if pi < np_ and (p[pi] == "?" or p[pi] == s[si]):
            pi += 1
            si += 1
        elif pi < np_ and p[pi] == "*":
            star = pi
            star_si = si
            pi += 1
        elif star != -1:
            pi = star + 1
            star_si += 1
            si = star_si
        else:
            return False
    while pi < np_ and p[pi] == "*":
        pi += 1
    return pi == np_


def has_wildcards(s: str) -> bool:
    """True if the string contains glob metacharacters (wildcards.go:36)."""
    return "*" in s or "?" in s
