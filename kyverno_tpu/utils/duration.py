"""Go-style duration parsing for the Duration* condition operators.

Mirrors time.ParseDuration as used by the precondition operator handlers
(/root/reference/pkg/engine/variables/operator/duration.go). Returns seconds
as a float. Also accepts bare numbers (treated as seconds), matching the
reference operator's fallback for numeric operands.
"""

from __future__ import annotations

import re

_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,  # µs
    "μs": 1e-6,  # μs
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}

_PART = re.compile(r"(\d+(?:\.\d*)?|\.\d+)(ns|us|µs|μs|ms|s|m|h)")


class DurationError(ValueError):
    pass


def parse_duration(s: str) -> float:
    """Parse "1h30m", "300ms", "-1.5h" etc. into seconds."""
    if not isinstance(s, str):
        raise DurationError(f"not a string: {s!r}")
    orig = s
    s = s.strip()
    neg = False
    if s and s[0] in "+-":
        neg = s[0] == "-"
        s = s[1:]
    if s == "0":
        return 0.0
    if not s:
        raise DurationError(f"invalid duration: {orig!r}")
    total = 0.0
    pos = 0
    for m in _PART.finditer(s):
        if m.start() != pos:
            raise DurationError(f"invalid duration: {orig!r}")
        total += float(m.group(1)) * _UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise DurationError(f"invalid duration: {orig!r}")
    return -total if neg else total
