from .wildcard import wildcard_match
from .quantity import parse_quantity, compare_quantities
from .duration import parse_duration

__all__ = ["wildcard_match", "parse_quantity", "compare_quantities", "parse_duration"]
