"""Offline renderer for the deploy chart's Helm-template subset.

``deploy/chart/kyverno-tpu`` is a standard Helm chart (``helm template``
renders it unchanged); this module renders the same output without the
helm binary, so air-gapped environments — and this repo's CI — can
produce install manifests from chart values. Supported constructs are
the subset the chart uses: ``{{ .Values.* }}`` / ``.Chart`` /
``.Release`` lookups, ``include`` of ``define`` blocks from
``_helpers.tpl``, ``if``/``else``/``end`` with Helm truthiness, and the
``default``/``quote``/``toYaml``/``indent``/``nindent`` pipeline
functions, with ``{{-``/``-}}`` whitespace control.
"""

from __future__ import annotations

import json
import re
from pathlib import Path

import yaml

_ACTION = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.S)


# ------------------------------------------------------------------ parse


def _tokenize(src: str):
    """-> [("text", s) | ("action", expr)] with whitespace control
    applied (a ``-`` eats adjacent whitespace including the newline)."""
    out = []
    pos = 0
    for m in _ACTION.finditer(src):
        text = src[pos:m.start()]
        if m.group(1) == "-":
            text = text.rstrip()
        out.append(("text", text))
        out.append(("action", m.group(2), m.group(3) == "-"))
        pos = m.end()
    out.append(("text", src[pos:]))
    # right-trim marker eats following whitespace up to and incl. newline
    merged = []
    strip_next = False
    for tok in out:
        if tok[0] == "text":
            text = tok[1]
            if strip_next:
                text = re.sub(r"^[ \t]*\n?", "", text, count=1)
                strip_next = False
            merged.append(("text", text))
        else:
            merged.append(("action", tok[1]))
            strip_next = tok[2]
    return merged


def _parse(tokens, i=0, until=()):
    """Token list -> node tree. Nodes: ("text", s), ("expr", s),
    ("if", cond, then_nodes, else_nodes), ("define", name, nodes)."""
    nodes = []
    while i < len(tokens):
        tok = tokens[i]
        if tok[0] == "text":
            nodes.append(tok)
            i += 1
            continue
        expr = tok[1]
        if expr.startswith("/*"):
            # Go-template comment: valid Helm, renders as nothing
            i += 1
            continue
        word = expr.split(None, 1)[0] if expr else ""
        if word in until:
            return nodes, i
        i += 1
        if word in ("range", "with", "block", "template") or \
                word.startswith("$"):
            # constructs outside the supported subset must fail LOUDLY:
            # rendering `range` as literal text would produce a manifest
            # that LOOKS valid while helm template disagrees (the drift
            # the golden-render test exists to catch)
            raise ValueError(f"unsupported template construct: {expr!r}")
        if word == "if":
            then, i = _parse(tokens, i, until=("else", "end"))
            els = []
            if tokens[i][1].split(None, 1)[0] == "else":
                if tokens[i][1].strip() != "else":
                    # `else if` would silently render as a plain else —
                    # fail loudly like every other unsupported construct
                    raise ValueError(
                        f"unsupported template construct: {tokens[i][1]!r}")
                els, i = _parse(tokens, i + 1, until=("end",))
            i += 1  # consume end
            nodes.append(("if", expr.split(None, 1)[1], then, els))
        elif word == "define":
            name = expr.split(None, 1)[1].strip().strip('"')
            body, i = _parse(tokens, i, until=("end",))
            i += 1
            nodes.append(("define", name, body))
        else:
            nodes.append(("expr", expr))
    return nodes, i


# ------------------------------------------------------------------- eval


def _truthy(v) -> bool:
    return not (v is None or v is False or v == "" or v == {} or v == []
                or v == 0)


def _lookup(path: str, ctx: dict):
    cur = ctx
    for seg in path.lstrip(".").split("."):
        if not seg:
            continue
        if not isinstance(cur, dict) or seg not in cur:
            return None
        cur = cur[seg]
    return cur


def _split_args(s: str) -> list[str]:
    """Split on spaces outside double quotes and parentheses, keeping a
    ``(...)`` group (a sub-pipeline) as one token."""
    out = []
    i = 0
    n = len(s)
    while i < n:
        c = s[i]
        if c.isspace():
            i += 1
        elif c == '"':
            j = s.index('"', i + 1)
            out.append(s[i:j + 1])
            i = j + 1
        elif c == "(":
            depth = 1
            j = i + 1
            while j < n and depth:
                if s[j] == "(":
                    depth += 1
                elif s[j] == ")":
                    depth -= 1
                j += 1
            out.append(s[i:j])
            i = j
        else:
            j = i
            while j < n and not s[j].isspace() and s[j] not in '"(':
                j += 1
            out.append(s[i:j])
            i = j
    return out


def _split_pipeline(s: str) -> list[str]:
    """Split on | outside quotes/parens."""
    out = []
    depth = 0
    in_str = False
    start = 0
    for i, c in enumerate(s):
        if c == '"':
            in_str = not in_str
        elif not in_str and c == "(":
            depth += 1
        elif not in_str and c == ")":
            depth -= 1
        elif not in_str and c == "|" and depth == 0:
            out.append(s[start:i].strip())
            start = i + 1
    out.append(s[start:].strip())
    return out


class Renderer:
    def __init__(self, defines: dict, ctx: dict):
        self.defines = defines
        self.ctx = ctx

    def render(self, nodes) -> str:
        out = []
        for node in nodes:
            if node[0] == "text":
                out.append(node[1])
            elif node[0] == "expr":
                val = self.eval_pipeline(node[1])
                out.append("" if val is None else str(val))
            elif node[0] == "if":
                branch = node[2] if _truthy(
                    self.eval_pipeline(node[1])) else node[3]
                out.append(self.render(branch))
            elif node[0] == "define":
                pass  # collected separately
        return "".join(out)

    def eval_pipeline(self, expr: str):
        stages = _split_pipeline(expr)
        val = self._eval_primary(stages[0])
        for stage in stages[1:]:
            val = self._apply(stage, val)
        return val

    def _eval_primary(self, expr: str):
        args = _split_args(expr)
        if not args:
            return None
        head = args[0]
        if head.startswith("("):
            return self.eval_pipeline(head[1:-1])
        if head == "include":
            name = args[1].strip('"')
            if name not in self.defines:
                raise KeyError(f"no template named {name}")
            return self.render(self.defines[name]).strip("\n")
        if head.startswith('"'):
            return head.strip('"')
        if head.startswith("."):
            return _lookup(head, self.ctx)
        if head == "true":
            return True
        if head == "false":
            return False
        if head == "nil":
            return None
        try:
            return int(head)
        except ValueError:
            pass
        # bare words are not values in Go templates — an unknown function
        # or sprig call here must fail loudly, never render as its own name
        raise ValueError(f"unsupported template expression: {expr!r}")

    def _apply(self, stage: str, val):
        args = _split_args(stage)
        fn, rest = args[0], args[1:]
        if fn == "default":
            fallback = self._eval_primary(" ".join(rest))
            return val if _truthy(val) else fallback
        if fn == "quote":
            return json.dumps("" if val is None else str(val))
        if fn == "toYaml":
            return yaml.safe_dump(val, default_flow_style=False).rstrip("\n")
        if fn in ("indent", "nindent"):
            n = int(rest[0])
            pad = " " * n
            body = "\n".join(pad + line if line else line
                             for line in str(val).splitlines())
            return ("\n" + body) if fn == "nindent" else body
        if fn == "toString":
            return "" if val is None else str(val)
        raise ValueError(f"unsupported template function: {fn}")


# ------------------------------------------------------------------ chart


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in (over or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _apply_set(values: dict, assignment: str) -> None:
    """--set a.b.c=value (YAML-parsed scalar)."""
    path, _, raw = assignment.partition("=")
    cur = values
    keys = path.split(".")
    for key in keys[:-1]:
        cur = cur.setdefault(key, {})
    cur[keys[-1]] = yaml.safe_load(raw) if raw != "" else ""


def render_chart(chart_dir: str | Path, values_override: dict | None = None,
                 set_args: list[str] | None = None,
                 release_name: str = "kyverno-tpu",
                 release_namespace: str = "") -> list[dict]:
    """Render every template -> list of parsed manifest documents,
    the ``helm template`` equivalent."""
    chart_dir = Path(chart_dir)
    chart = yaml.safe_load((chart_dir / "Chart.yaml").read_text())
    values = yaml.safe_load((chart_dir / "values.yaml").read_text()) or {}
    values = _deep_merge(values, values_override or {})
    for assignment in set_args or []:
        _apply_set(values, assignment)

    ctx = {
        "Values": values,
        "Chart": {"Name": chart.get("name", ""),
                  "Version": str(chart.get("version", "")),
                  "AppVersion": str(chart.get("appVersion", ""))},
        "Release": {"Name": release_name,
                    "Namespace": release_namespace
                    or values.get("namespace") or "default",
                    "Service": "Helm"},
    }

    defines: dict = {}
    templates = sorted((chart_dir / "templates").glob("*"))
    parsed = []
    for path in templates:
        nodes, _ = _parse(_tokenize(path.read_text()))
        for node in nodes:
            if node[0] == "define":
                defines[node[1]] = node[2]
        if path.suffix in (".yaml", ".yml"):
            parsed.append(nodes)

    renderer = Renderer(defines, ctx)
    docs: list[dict] = []
    for nodes in parsed:
        text = renderer.render(nodes)
        for doc in yaml.safe_load_all(text):
            if doc:
                docs.append(doc)
    return docs
