"""Number<->string conversions matching the reference engine's Go formatting.

The leaf comparator stringifies resource values before wildcard/quantity
comparison; byte-identical formatting matters for conformance (e.g. a float
2.5 must become "2.500000" on the quantity path and "2.5E+00" on the string
equality path, as in /root/reference/pkg/engine/validate/pattern.go:219,265
and validate/common.go:9).
"""

from __future__ import annotations


def format_float_fixed(v: float) -> str:
    """Go fmt.Sprintf("%f", v): fixed-point, 6 decimals."""
    return f"{v:f}"


def format_float_sci(v: float) -> str:
    """Go strconv.FormatFloat(v, 'E', -1, 64): shortest round-trip mantissa,
    capital E, >=2-digit exponent."""
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    mant, _, exp = f"{v:E}".partition("E")
    # shortest round-trip: use repr() which is shortest, then re-derive
    shortest = repr(float(v))
    if "e" in shortest or "E" in shortest:
        m, _, e = shortest.lower().partition("e")
        mant = m
        iexp = int(e)
    else:
        neg = shortest.startswith("-")
        digits = shortest.lstrip("-")
        int_part, _, frac_part = digits.partition(".")
        frac_part = frac_part.rstrip("0") if frac_part != "0" else ""
        if int_part == "0":
            # 0.00123 -> 1.23E-03
            stripped = frac_part.lstrip("0")
            if not stripped:
                return "-0E+00" if neg else "0E+00"
            iexp = -(len(frac_part) - len(stripped) + 1)
            mant_digits = stripped
        else:
            iexp = len(int_part) - 1
            mant_digits = (int_part + frac_part).rstrip("0") or "0"
        mant = mant_digits[0] + ("." + mant_digits[1:] if len(mant_digits) > 1 else "")
        if neg:
            mant = "-" + mant
    sign = "+" if iexp >= 0 else "-"
    return f"{mant}E{sign}{abs(iexp):02d}"


def convert_number_to_string(value) -> str | None:
    """validate/common.go:9 convertNumberToString; None return => not convertible."""
    if value is None:
        return "0"
    if isinstance(value, bool):
        return None
    if isinstance(value, str):
        return value
    if isinstance(value, float):
        return format_float_fixed(value)
    if isinstance(value, int):
        return str(value)
    return None


def value_to_string_for_equality(value) -> str | None:
    """pattern.go:210-232 validateString value stringification; None => fail."""
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, float):
        return format_float_sci(value)
    if isinstance(value, int):
        return str(value)
    if isinstance(value, str):
        return value
    return None
