"""Fast deep copy for JSON trees.

``copy.deepcopy`` pays for memoization and type dispatch that pure JSON
documents (dict/list/scalars, no cycles) never need; profiling shows it
dominating the mutation hot path (Context.add_resource / merge_patch /
checkpoint). ``json_copy`` is the 3-5x cheaper specialization, falling
back to ``copy.deepcopy`` for any non-JSON node it encounters.
"""

from __future__ import annotations

import copy

_SCALARS = (str, int, float, bool, type(None))


def json_copy(x, _memo: dict | None = None):
    """Deep copy preserving shared subtrees (YAML anchors/aliases load as
    shared objects; copying each occurrence separately would blow up
    billion-laughs-style documents and recurse forever on self-references,
    so containers are memoized by id like copy.deepcopy does)."""
    tx = type(x)
    if tx is dict:
        if _memo is None:
            _memo = {}
        got = _memo.get(id(x))
        if got is not None:
            return got
        out: dict = {}
        _memo[id(x)] = out
        for k, v in x.items():
            out[k] = json_copy(v, _memo)
        return out
    if tx is list:
        if _memo is None:
            _memo = {}
        got = _memo.get(id(x))
        if got is not None:
            return got
        out_l: list = []
        _memo[id(x)] = out_l
        for v in x:
            out_l.append(json_copy(v, _memo))
        return out_l
    if tx in _SCALARS or isinstance(x, _SCALARS):
        return x
    return copy.deepcopy(x, _memo)
