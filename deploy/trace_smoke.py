"""Tracing smoke for CI (deploy/ci_lint.sh).

Drives an admission burst through :class:`AdmissionBatcher` twice —
tracing on (default) and ``KTPU_TRACE=0`` — and fails if:

1. the verdicts differ (the recorder must be a pure observer),
2. any traced admission is missing a pipeline stage (flatten, coalesce
   wait, device dispatch/compile, host lane, scatter),
3. any span is an orphan (falls outside its trace's [start, end] window
   or carries a negative duration),
4. the ``/metrics`` exposition fails a minimal text-0.0.4 parse, or its
   stage histograms are missing the cumulative ``le=`` / ``+Inf`` lines.

Fast by construction: one policy, a few dozen admissions, CPU backend.
Exit 0 = OK, 1 = any gate failed.
"""

import json
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# stages every live (non-probe) flush-served admission must traverse;
# device_dispatch and xla_compile are alternates for the same boundary
REQUIRED_STAGES = ("coalesce_wait", "flatten", "host_resolve", "scatter")

# text 0.0.4 sample line: name{labels} value  (labels optional)
_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'            # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' [0-9eE.+-]+(?:[iI]nf)?$')


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default"},
            "spec": {"containers": [{"name": "c",
                                     "image": ("nginx:latest" if i % 5 == 0
                                               else f"nginx:1.{i}")}]}}


def _burst(n=48, rec=None):
    """Screen n pods through one batcher — each screen inside its own
    admission trace when ``rec`` is given. Returns the verdict list."""
    import concurrent.futures

    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.runtime import tracing
    from kyverno_tpu.runtime.batch import AdmissionBatcher
    from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

    cache = PolicyCache()
    cache.add(load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "no-latest"},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "m", "pattern": {
                "spec": {"containers": [{"image": "!*:latest"}]}}},
        }]},
    }))
    batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                               dispatch_cost_init_s=0.0,
                               oracle_cost_init_s=1.0,
                               cold_flush_fallback=False,
                               result_cache_ttl_s=0.0)

    def one(i):
        if rec is None:
            return batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                  "default", _pod(i))
        t = rec.start("admission", i=i)
        with tracing.active(t):
            out = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                 "default", _pod(i))
        rec.finish(t)
        return out

    try:
        # warm one admission so the burst takes the warm async lane
        batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                       _pod(1000))
        with concurrent.futures.ThreadPoolExecutor(8) as ex:
            return list(ex.map(one, range(n)))
    finally:
        batcher.stop()


def _traced_burst(n=48):
    from kyverno_tpu.runtime import tracing

    rec = tracing.recorder()
    rec.clear()
    verdicts = _burst(n, rec=rec)
    admissions = [t for t in rec.traces(4 * n) if t.kind == "admission"]
    return verdicts, admissions


def main() -> int:
    from kyverno_tpu.runtime import obs_http

    os.environ.pop("KTPU_TRACE", None)
    traced, admissions = _traced_burst()

    os.environ["KTPU_TRACE"] = "0"
    try:
        untraced = _burst()
    finally:
        os.environ.pop("KTPU_TRACE", None)

    # gate 1: verdict parity — tracing must not change a single verdict
    if traced != untraced:
        bad = sum(1 for a, b in zip(traced, untraced) if a != b)
        print(f"trace_smoke: VERDICT DIVERGENCE on {bad} admissions "
              f"with tracing on vs off", file=sys.stderr)
        return 1

    # gate 2: stage coverage — every traced admission shows the pipeline
    if not admissions:
        print("trace_smoke: no admission traces recorded", file=sys.stderr)
        return 1
    for t in admissions:
        names = t.stage_names()
        missing = [s for s in REQUIRED_STAGES if s not in names]
        if "device_dispatch" not in names and "xla_compile" not in names:
            missing.append("device_dispatch|xla_compile")
        if missing:
            print(f"trace_smoke: trace {t.trace_id} missing stages "
                  f"{missing} (has {sorted(names)})", file=sys.stderr)
            return 1

    # gate 3: no orphan spans — every span inside its trace's window,
    # with a non-negative duration
    for t in admissions:
        for s in t.spans:
            if s.t1 < s.t0 - 1e-9:
                print(f"trace_smoke: span {s.name} negative duration",
                      file=sys.stderr)
                return 1
            if s.t0 < t.t_start - 1e-6 or s.t1 > t.t_end + 1e-6:
                print(f"trace_smoke: ORPHAN span {s.name} outside trace "
                      f"{t.trace_id} window", file=sys.stderr)
                return 1

    # gate 4: /metrics parses under a minimal text-0.0.4 parser and the
    # stage histogram exposes cumulative le= buckets ending in +Inf
    status, body, ctype = obs_http.handle_obs_get("/metrics")
    if status != 200 or not ctype.startswith("text/plain"):
        print("trace_smoke: /metrics did not serve text/plain 200",
              file=sys.stderr)
        return 1
    text = body.decode()
    for ln, line in enumerate(text.splitlines(), 1):
        if not line or line.startswith("#"):
            continue
        if not _LINE.match(line):
            print(f"trace_smoke: /metrics line {ln} fails text-format "
                  f"parse: {line!r}", file=sys.stderr)
            return 1
    buckets = [l for l in text.splitlines()
               if l.startswith("kyverno_stage_duration_seconds_bucket")]
    if not buckets or not any('le="+Inf"' in l for l in buckets):
        print("trace_smoke: stage histogram missing _bucket/+Inf lines",
              file=sys.stderr)
        return 1

    # gate 5: /healthz carries the stream-plane and SLO state next to
    # the lane matrix (the fleet-observability keys dashboards read)
    status, body, ctype = obs_http.handle_obs_get("/healthz")
    if status != 200:
        print("trace_smoke: /healthz did not serve 200", file=sys.stderr)
        return 1
    health = json.loads(body)
    for key in ("status", "lanes", "streams", "slo"):
        if key not in health:
            print(f"trace_smoke: /healthz missing key {key!r} "
                  f"(has {sorted(health)})", file=sys.stderr)
            return 1
    for key in ("open_streams", "inflight_batch_fill", "continuous"):
        if key not in health["streams"]:
            print(f"trace_smoke: /healthz streams missing {key!r}",
                  file=sys.stderr)
            return 1
    if health["slo"].get("enabled") and "burn_rate" not in health["slo"]:
        print("trace_smoke: /healthz slo enabled but missing burn_rate",
              file=sys.stderr)
        return 1

    # sanity: the chrome export of the burst is valid JSON
    from kyverno_tpu.runtime import tracing

    doc = json.loads(json.dumps(tracing.recorder().chrome_trace(16)))
    n_events = len(doc["traceEvents"])

    n_spans = sum(len(t.spans) for t in admissions)
    print(f"trace_smoke: OK ({len(admissions)} admission traces, "
          f"{n_spans} spans, verdict parity on/off, "
          f"{len(buckets)} stage bucket lines, "
          f"{n_events} chrome events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
