"""Fleet verdict-fabric smoke for CI (deploy/ci_lint.sh).

Proves the PR-15 fleet contract on a repeat-heavy synthetic trace
played through in-process replica pools (workload/replay.py
``build_fleet_stacks`` / ``run_fleet``):

1. kill switch — with ``KTPU_FABRIC=0`` a 1-replica and a 2-replica
   fleet reproduce each other's per-event decisions exactly (allowed
   bit + violated policy/rule attribution; the failure prose is
   lane-dependent by design, see ``_verdict_map``) and the shared hub
   sees nothing beyond the epoch-sync handshakes (no hits, no puts);
2. fabric parity + sharing — with the fabric on, a 2-replica
   no-affinity run (repeated bodies landing on *different* replicas)
   matches the kill-switch decision map exactly and serves > 0
   cross-replica cache hits (the affinity routing path is exercised by
   the churn gate's 2-replica run);
3. churn invalidation — a policy-churn trace propagates invalidation
   fleet-wide (hub epoch bumps, rows purge) while 1-vs-2 replica
   verdict digests stay identical;
4. transport — ``KTPU_FABRIC_TRANSPORT=socket`` (hub behind a framed
   loopback socket) reproduces the inproc verdict map byte-for-byte;
5. manifests — topology-mismatched runs diff as incomparable
   (numeric deltas suppressed) while verdict parity still compares;
6. partitioned scan + takeover — three FleetScanCoordinators split
   ``KTPU_SCAN_PARTITIONS`` ranges via named leases; the merged
   per-range digests equal an unpartitioned scan's, and killing a
   member mid-protocol reassigns its ranges to the survivors (lease
   expiry → rendezvous reassignment → part-lease takeover) with the
   full range set re-covered and digest parity intact.

Fast by construction: CPU backend, two pattern policies, ~100 trace
events per run. ``FLEET_SMOKE_QUICK=1`` (the double-invocation
``test_ci_lint_script_gates_on_injected_error`` budget, same idiom as
``CI_LINT_FUZZ_CASES``) trims the traces further and skips the socket
gate — the socket transport keeps unit coverage in
``tests/fleet/test_fabric.py``. Exit 0 = parity, 1 = divergence.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["KTPU_REPLAY"] = "1"
for _var in ("KTPU_FABRIC", "KTPU_FABRIC_TRANSPORT",
             "KTPU_SCAN_PARTITIONS"):
    os.environ.pop(_var, None)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _policy(name, pattern):
    return {
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": f"{name} violated",
                         "pattern": pattern},
        }]},
    }


BASE_DOCS = [
    # denies the ":latest" bodies trace.synthesize emits every 4th
    # variant — a mixed allow/deny stream, not a constant verdict
    _policy("no-latest", {"spec": {"containers": [{"image": "!*:latest"}]}}),
    _policy("need-team", {"metadata": {"labels": {"team": "?*"}}}),
]
# churn doc: flips v1-tagged bodies from allow to deny mid-trace
CHURN_DOC = _policy("no-v1", {"spec": {"containers": [{"image": "!*:v1"}]}})


def _fleet_run(policies, trace, replicas, affinity=True):
    from kyverno_tpu.workload import replay

    fleet = replay.build_fleet_stacks(
        [_load(doc) for doc in policies], replicas=replicas)
    try:
        out = replay.run_fleet(trace, fleet, workers=4, affinity=affinity)
        out["topology"] = replay.current_topology(fleet)
    finally:
        replay.stop_fleet_stacks(fleet)
    return out


def _load(doc):
    from kyverno_tpu.api.load import load_policy

    return load_policy(doc)


def _verdict_map(result):
    """Per-event decision map: allowed bit + the sorted set of violated
    policy/rule pairs. The raw failure TEXT is lane-dependent by design
    (a device-decided cell emits the compact webhook form, a
    flush-resolved host cell carries the oracle's path-qualified form,
    and which lane answers a borderline cell is a latency-router
    decision) — the decision and its attribution are the
    replica-parity contract, the prose is not."""
    import re

    out = {}
    for seq, v in result["verdicts"].items():
        out[seq] = {"allowed": v["allowed"],
                    "violations": sorted(set(re.findall(
                        r"policy [\w.-]+/[\w.-]+", v.get("detail") or "")))}
    return json.dumps(out, sort_keys=True)


def main() -> int:  # noqa: C901 - linear gate script
    from kyverno_tpu.fleet import scanparts
    from kyverno_tpu.runtime import leaderelection as le_mod
    from kyverno_tpu.runtime import metrics as metrics_mod
    from kyverno_tpu.runtime.background import BackgroundScanner
    from kyverno_tpu.runtime.client import FakeCluster
    from kyverno_tpu.runtime.obs_http import handle_obs_get
    from kyverno_tpu.workload import replay, trace as trace_mod

    failures = []

    def check(name, ok, detail=""):
        tag = "ok" if ok else "FAIL"
        print(f"[fleet_smoke] {tag:4s} {name}" + (f" ({detail})"
                                                  if detail else ""))
        if not ok:
            failures.append(name)

    quick = os.environ.get("FLEET_SMOKE_QUICK") == "1"
    n_events = 72 if quick else 120
    if quick:
        print("[fleet_smoke] quick mode: trimmed traces, socket gate "
              "skipped (unit-covered in tests/fleet)")

    # repeat-heavy admission trace: no update/delete churn so decision
    # keys repeat across events (the lane the shared fabric serves)
    tr = trace_mod.synthesize(events=n_events, namespaces=4,
                              distinct_bodies=6, update_fraction=0.0,
                              delete_fraction=0.0, name_pool=4, seed=7)
    churn_tr = trace_mod.synthesize(events=n_events, namespaces=4,
                                    distinct_bodies=6,
                                    update_fraction=0.0,
                                    delete_fraction=0.0, name_pool=4,
                                    policy_docs=[CHURN_DOC],
                                    policy_churn_every=n_events // 2 - 10,
                                    seed=11)

    # ---- gate 1: kill switch (KTPU_FABRIC unset = off) ----------------
    off1 = _fleet_run(BASE_DOCS, tr, replicas=1)
    off2 = _fleet_run(BASE_DOCS, tr, replicas=2)
    check("killswitch 1-vs-2 decision maps equal (allowed + violations)",
          _verdict_map(off1) == _verdict_map(off2),
          f"digest {off1['verdict_digest']}")
    check("killswitch run saw denials", off1["denied"] > 0,
          f"denied={off1['denied']}")
    hub_off = off2["hub"]
    check("killswitch hub dormant (sync handshakes only)",
          hub_off["puts"] == 0 and hub_off["hits"] == 0
          and hub_off["gets"] == 2,
          f"hub={hub_off}")
    check("killswitch runs clean",
          not off1["errors"] and not off2["errors"])

    # ---- gate 2: fabric on — parity + cross-replica sharing -----------
    os.environ["KTPU_FABRIC"] = "1"
    # no-affinity: repeats of one body land on different replicas, so
    # only the shared fabric (never the local caches) can serve them
    on2_spread = _fleet_run(BASE_DOCS, tr, replicas=2, affinity=False)
    check("fabric-on matches kill-switch decision map",
          _verdict_map(on2_spread) == _verdict_map(off1))
    check("cross-replica fabric hits > 0 (no-affinity routing)",
          on2_spread["fabric_hits"] > 0,
          f"hits={on2_spread['fabric_hits']} "
          f"rate={on2_spread['fabric_hit_rate']}")
    check("hub accepted publishes", on2_spread["hub"]["puts"] > 0,
          f"puts={on2_spread['hub']['puts']}")
    reg = metrics_mod.registry()
    check("kyverno_fabric_* counters live",
          (reg.counter_total("kyverno_fabric_frames_total") or 0) > 0
          and (reg.counter_total("kyverno_fabric_hits_total") or 0) > 0)
    health = json.loads(handle_obs_get("/healthz")[1])
    check("/healthz fleet block reports fabric",
          health.get("fleet", {}).get("enabled") is True,
          f"fleet={health.get('fleet', {}).get('enabled')}")

    # ---- gate 3: churn invalidation propagation -----------------------
    ch1 = _fleet_run(BASE_DOCS, churn_tr, replicas=1)
    ch2 = _fleet_run(BASE_DOCS, churn_tr, replicas=2)
    check("churn 1-vs-2 verdict digests identical",
          ch1["verdict_digest"] == ch2["verdict_digest"],
          f"digest {ch2['verdict_digest']}")
    check("churn drove fleet-wide invalidation",
          ch2["hub"]["invalidations"] > 0 and ch2["hub"]["epoch"] > 0,
          f"invalidations={ch2['hub']['invalidations']} "
          f"epoch={ch2['hub']['epoch']}")
    check("churn runs clean with denials",
          not ch1["errors"] and not ch2["errors"] and ch1["denied"] > 0,
          f"denied={ch1['denied']}")

    # ---- gate 4: socket transport parity ------------------------------
    if not quick:
        os.environ["KTPU_FABRIC_TRANSPORT"] = "socket"
        sock2 = _fleet_run(BASE_DOCS, tr, replicas=2, affinity=False)
        os.environ.pop("KTPU_FABRIC_TRANSPORT", None)
        check("socket transport decision map equal to inproc",
              _verdict_map(sock2) == _verdict_map(on2_spread))
        check("socket transport served fabric traffic",
              sock2["hub"]["frames"] > 2 and sock2["fabric_hits"] > 0,
              f"frames={sock2['hub']['frames']} "
              f"hits={sock2['fabric_hits']}")

    # ---- gate 5: manifests — topology-aware diff ----------------------
    m1 = replay.run_manifest(tr, [off1], topology=off1["topology"])
    m2 = replay.run_manifest(tr, [on2_spread],
                             topology=on2_spread["topology"])
    diff = replay.diff_manifests(m1, m2)
    leg = diff["legs"]["fleet_stream"]
    check("1-vs-2 manifest diff: verdict parity compared, deltas skipped",
          leg.get("verdict_parity") is True
          and leg.get("skipped") == "topology mismatch"
          and diff["topology"]["comparable"] is False,
          f"leg={leg}")

    # ---- gate 6: partitioned scan + lease takeover --------------------
    os.environ["KTPU_SCAN_PARTITIONS"] = "5"
    n_parts = scanparts.scan_partition_count()
    saved = (le_mod.LEASE_DURATION_S, le_mod.RENEW_DEADLINE_S)
    le_mod.LEASE_DURATION_S, le_mod.RENEW_DEADLINE_S = 0.25, 0.2
    try:
        policies = [_load(doc) for doc in BASE_DOCS]
        resources = []
        for i in range(40):
            ns = f"team-{i % 8}"
            tag = "latest" if i % 4 == 3 else f"v{i % 7}"
            resources.append({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"pod-{i}", "namespace": ns,
                             "labels": {"team": ns}},
                "spec": {"containers": [{"name": "c",
                                         "image": f"nginx:{tag}"}]}})

        baseline = BackgroundScanner(policies)
        baseline.scan(resources)
        base_digest = scanparts.merge_range_digests(
            scanparts.matrix_range_digests(baseline, n_parts))

        cluster = FakeCluster()
        coords = {name: scanparts.FleetScanCoordinator(
            cluster, identity=name) for name in ("r0", "r1", "r2")}
        scanners = {name: BackgroundScanner(policies) for name in coords}
        for _ in range(3):   # leader elects, publishes, members enroll
            for c in coords.values():
                c.tick()
        owned = {n: set(c.owned_partitions()) for n, c in coords.items()}
        all_owned = set().union(*owned.values())
        check("partition protocol covers full range set",
              all_owned == set(range(n_parts))
              and sum(len(o) for o in owned.values()) == n_parts,
              f"owned={ {n: sorted(o) for n, o in owned.items()} }")

        digests = {}
        for name, c in coords.items():
            _, d = scanparts.scan_partitions(
                scanners[name], resources, c.owned_partitions(), n_parts)
            digests[name] = d
        check("partitioned scan digest == unpartitioned",
              scanparts.merge_range_digests(*digests.values())
              == base_digest, f"base={base_digest}")
        check("per-range row gauge published",
              any(reg.gauge_value("kyverno_scan_partition_rows",
                                  {"range": str(p)}) is not None
                  for p in range(n_parts)))

        # crash a member that owns ranges: simply stop ticking it, so
        # nothing renews and its member/part leases must *expire* (the
        # hard takeover path — no graceful release). If every owner
        # leads, leadership takeover is part of the exercise.
        victims = [n for n, c in coords.items()
                   if owned[n] and not c.elector.is_leader()]
        victim = victims[0] if victims else next(
            n for n in coords if owned[n])
        dead_ranges = owned.pop(victim)
        coords.pop(victim)
        time.sleep(le_mod.LEASE_DURATION_S + 0.1)
        for _ in range(3):   # roster shrinks, reassignment, takeover
            for c in coords.values():
                c.tick()
        owned2 = {n: set(c.owned_partitions()) for n, c in coords.items()}
        check("survivors re-cover full range set after member loss",
              set().union(*owned2.values()) == set(range(n_parts))
              and sum(len(o) for o in owned2.values()) == n_parts,
              f"victim={victim} dead={sorted(dead_ranges)} "
              f"owned={ {n: sorted(o) for n, o in owned2.items()} }")

        digests2 = {}
        for name, c in coords.items():
            _, d = scanparts.scan_partitions(
                scanners[name], resources, c.owned_partitions(), n_parts)
            digests2[name] = d
        check("post-takeover merged digest == unpartitioned (no dropped "
              "rows)",
              scanparts.merge_range_digests(*digests2.values())
              == base_digest)
        for c in coords.values():
            c.stop()
    finally:
        le_mod.LEASE_DURATION_S, le_mod.RENEW_DEADLINE_S = saved
        os.environ.pop("KTPU_SCAN_PARTITIONS", None)
        os.environ.pop("KTPU_FABRIC", None)

    if failures:
        print(f"[fleet_smoke] FAILED: {failures}")
        return 1
    print("[fleet_smoke] all gates passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
