"""Streaming-plane smoke for CI (deploy/ci_lint.sh).

Brings up the webhook plane and the streaming plane on one batcher and
fails if any of these gates break:

1. **Webhook-vs-stream parity** — the same admissions produce the same
   allow/deny AND the same denial message through the HTTP webhook,
   through stream JSON frames, and (verdicts) through columnar ROW and
   BLOCK frames.
2. **Continuous-vs-window parity** — the burst rerun under
   ``KTPU_STREAM=0`` (window semantics, no late-join, no dict
   headroom) yields identical verdicts.
3. **Donation-did-not-corrupt** — a donated device dispatch returns
   verdicts identical to the undonated call and leaves the host-side
   packed blob bit-identical.

Fast by construction: one policy, a few dozen admissions, CPU backend.
Exit 0 = OK, 1 = any gate failed.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "no-latest"},
    "spec": {"validationFailureAction": "enforce", "rules": [{
        "name": "no-latest-tag",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"message": "latest tag not allowed",
                     "pattern": {"spec": {"containers": [
                         {"image": "!*:latest"}]}}},
    }]},
}


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default"},
            "spec": {"containers": [{"name": "c",
                                     "image": ("nginx:latest" if i % 5 == 0
                                               else f"nginx:1.{i}")}]}}


def _review(resource, uid):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": uid, "kind": {"kind": "Pod"},
                        "namespace": "default", "operation": "CREATE",
                        "object": resource}}


def _stack(continuous=True):
    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.runtime.batch import AdmissionBatcher
    from kyverno_tpu.runtime.client import FakeCluster
    from kyverno_tpu.runtime.policycache import PolicyCache
    from kyverno_tpu.runtime.webhook import WebhookServer

    cache = PolicyCache()
    cache.add(load_policy(POLICY))
    batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                               dispatch_cost_init_s=0.0,
                               oracle_cost_init_s=1.0,
                               cold_flush_fallback=False,
                               result_cache_ttl_s=0.0,
                               continuous=continuous)
    server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                           admission_batcher=batcher)
    return cache, batcher, server


def gate_parity(n=32) -> list[str]:
    """Webhook vs stream JSON vs columnar ROW vs BLOCK."""
    from kyverno_tpu.runtime.policycache import PolicyType
    from kyverno_tpu.runtime.stream_server import (StreamClient,
                                                   StreamServer,
                                                   flatten_block_for_wire,
                                                   flatten_rows_for_wire)
    from kyverno_tpu.runtime.webhook import VALIDATING_WEBHOOK_PATH

    failures = []
    cache, batcher, server = _stack()
    ss = StreamServer(server, batcher, cache).start()
    cl = StreamClient(ss.port, transport=ss.transport_name)
    try:
        pods = [_pod(i) for i in range(n)]
        webhook = [server.handle(VALIDATING_WEBHOOK_PATH,
                                 _review(p, f"w{i}"))["response"]
                   for i, p in enumerate(pods)]
        streamed = [cl.admit_json(_review(p, f"w{i}"))["response"]
                    for i, p in enumerate(pods)]
        for i, (a, b) in enumerate(zip(webhook, streamed)):
            if a != b:
                failures.append(f"json parity: pod {i}: {a} != {b}")
        cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        rows = flatten_rows_for_wire(cps, pods)
        for i, row in enumerate(rows):
            out = cl.admit_row("Pod", "default", row)
            if out["allowed"] != webhook[i]["allowed"]:
                failures.append(f"row parity: pod {i}: "
                                f"{out['allowed']} != "
                                f"{webhook[i]['allowed']}")
        block = flatten_block_for_wire(cps, pods)
        out = cl.admit_block("Pod", "default", block)
        if len(out["rows"]) != n:
            failures.append(f"block row count {len(out['rows'])} != {n}")
        for i, r in enumerate(out["rows"]):
            if r["allowed"] != webhook[i]["allowed"]:
                failures.append(f"block parity: pod {i}: "
                                f"{r['allowed']} != "
                                f"{webhook[i]['allowed']}")
        # denial messages: webhook and stream JSON must agree verbatim
        for i, (a, b) in enumerate(zip(webhook, streamed)):
            ma = (a.get("status") or {}).get("message", "")
            mb = (b.get("status") or {}).get("message", "")
            if ma != mb:
                failures.append(f"message parity: pod {i}: "
                                f"{ma!r} != {mb!r}")
    finally:
        cl.close()
        ss.stop()
        batcher.stop()
    return failures


def gate_window_parity(n=32) -> list[str]:
    """The same burst under KTPU_STREAM=0 (window semantics) and with
    continuous batching must produce identical verdict rows."""
    import concurrent.futures

    from kyverno_tpu.runtime.policycache import PolicyType

    def burst(env):
        os.environ.update(env)
        try:
            _, batcher, _ = _stack(continuous=True)
            try:
                with concurrent.futures.ThreadPoolExecutor(16) as pool:
                    # warm round first (discarded): pays the inline XLA
                    # compile of the flush shapes so the compared round
                    # can't hit a cold-stack screen timeout
                    warm = [pool.submit(
                        batcher.screen, PolicyType.VALIDATE_ENFORCE,
                        "Pod", "default", _pod(1000 + i))
                        for i in range(n)]
                    for f in warm:
                        f.result()
                    futs = [pool.submit(
                        batcher.screen, PolicyType.VALIDATE_ENFORCE,
                        "Pod", "default", _pod(i)) for i in range(n)]
                    return [f.result() for f in futs]
            finally:
                batcher.stop()
        finally:
            for k in env:
                os.environ.pop(k, None)

    cont = burst({})
    window = burst({"KTPU_STREAM": "0"})
    failures = []
    for i, (a, b) in enumerate(zip(cont, window)):
        if a != b:
            failures.append(f"window parity: pod {i}: {a} != {b}")
    return failures


def gate_donation(n=16) -> list[str]:
    """Donated dispatch: verdict parity with the undonated call, and
    the host-side packed blob survives untouched."""
    import numpy as np

    from kyverno_tpu.models.engine import DONATION_STATS
    from kyverno_tpu.runtime.policycache import PolicyType

    failures = []
    cache, batcher, _ = _stack()
    try:
        cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        block = cps.flatten_packed([_pod(i) for i in range(n)])
        blob, _ = block.packed_blob()
        snapshot = np.asarray(blob).copy()
        ref = np.asarray(cps.evaluate_device(block))
        before = DONATION_STATS["dispatches"]
        got = np.asarray(cps.evaluate_device_async(block,
                                                   donate=True).get())
        if DONATION_STATS["dispatches"] != before + 1:
            failures.append("donated dispatch did not run")
        if not np.array_equal(ref, got):
            failures.append("donation changed verdicts")
        after_blob, _ = block.packed_blob()
        if not np.array_equal(np.asarray(after_blob), snapshot):
            failures.append("donation corrupted the host-side blob")
    finally:
        batcher.stop()
    return failures


def main() -> int:
    failures = []
    failures += gate_parity()
    failures += gate_window_parity()
    failures += gate_donation()
    if failures:
        print("stream_smoke: FAILED")
        for f in failures[:20]:
            print("  -", f)
        return 1
    print("stream_smoke: OK (webhook/stream parity, KTPU_STREAM=0 "
          "parity, donation integrity)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
