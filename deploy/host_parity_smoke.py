"""Host-lane parity smoke for CI (deploy/ci_lint.sh).

Resolves the same HOST cells through four lanes and fails on any
verdict OR oracle-message difference:

  1. inline     — every KTPU_HOST_* kill switch thrown: the original
                  serial per-resource oracle walk
  2. prefetched — dispatch-time predictive prefetch joins at scatter
                  time (KTPU_HOST_PREFETCH), memo off
  3. memoized   — host-verdict memo warm after a fill pass
                  (KTPU_HOST_MEMO), answers must still match
  4. pooled     — resolution routed through OraclePool worker
                  processes (KTPU_HOST_FANOUT + attached pool)

Fast by construction: a few host-only policies, a handful of rows, CPU
backend — the point is the diff, not the throughput. The pooled lane
needs worker processes to spawn and warm; when the pool cannot come up
in this environment the lane is skipped with a note (the other three
still gate). Exit 0 = parity, 1 = divergence.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SWITCHES = ("KTPU_HOST_PREFETCH", "KTPU_HOST_MEMO", "KTPU_HOST_FANOUT")


def _set(prefetch, memo, fanout):
    for s, v in zip(SWITCHES, (prefetch, memo, fanout)):
        os.environ[s] = v


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default",
                         "uid": str(i)},
            "spec": {"containers": [{"name": "c", "image": f"nginx:1.{i}"}],
                     "hostNetwork": i % 2 == 0}}


def main() -> int:
    import numpy as np

    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.models import CompiledPolicySet
    from kyverno_tpu.runtime import hostlane

    policies = [load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": msg, "pattern": pattern},
        }]},
    }) for name, msg, pattern in (
        ("host-echo-name", "name mismatch",
         {"metadata": {"name": "{{request.object.metadata.name}}"}}),
        ("host-echo-ns", "namespace mismatch",
         {"metadata": {"namespace": "{{request.object.metadata.namespace}}"}}),
        ("host-never", "never matches",
         {"metadata": {"name": "{{request.object.metadata.uid}}"}}),
    )]
    cps = CompiledPolicySet(policies)
    docs = [_pod(i) for i in range(16)]
    ctxs = [{"request": {"object": d, "operation": "CREATE",
                         "userInfo": {"username": "smoke"}}} for d in docs]

    def lane(use_prefetch):
        msgs = {}
        v = np.asarray(cps.evaluate_device(cps.flatten_packed(docs)))
        pf = hostlane.resolver().prefetch(cps, docs, contexts=ctxs)
        v = cps.resolve_host_cells(docs, v, contexts=ctxs,
                                   messages_out=msgs, prefetch=pf)
        assert (pf is not None) == use_prefetch, \
            f"prefetch handle mismatch (expected started={use_prefetch})"
        return np.asarray(v), msgs

    lanes = {}
    _set("0", "0", "0")
    lanes["inline"] = lane(use_prefetch=False)
    _set("1", "0", "0")
    lanes["prefetched"] = lane(use_prefetch=True)
    _set("1", "1", "0")
    hostlane.host_cache().clear()
    lane(use_prefetch=True)                       # memo fill pass
    lanes["memoized"] = lane(use_prefetch=True)
    memo_stats = hostlane.host_cache().stats()

    # pooled lane: spawn real worker processes (min_cores=1: the gate
    # exists for production sizing, not for this smoke)
    pool = None
    pool_note = ""
    try:
        from kyverno_tpu.runtime.oracle_pool import OraclePool
        from kyverno_tpu.runtime.policycache import PolicyCache

        cache = PolicyCache()
        for p in policies:
            cache.add(p)
        pool = OraclePool(min_cores=1, workers=2)
        gen, pols = cache.snapshot()
        pool.ensure(gen, pols)
        deadline = time.monotonic() + 60
        while not pool.ready(gen) and time.monotonic() < deadline:
            time.sleep(0.25)
        if pool.ready(gen):
            pooled_cps = CompiledPolicySet(pols)
            r = hostlane.resolver()
            r.attach_pool(pool, cache)
            _set("1", "0", "1")
            before = r.stats["pool_cells"]
            msgs = {}
            v = np.asarray(pooled_cps.evaluate_device(
                pooled_cps.flatten_packed(docs)))
            v = pooled_cps.resolve_host_cells(docs, v, contexts=ctxs,
                                              messages_out=msgs)
            lanes["pooled"] = (np.asarray(v), msgs)
            pool_note = f"pool_cells={r.stats['pool_cells'] - before}"
        else:
            pool_note = "pool never became ready; pooled lane skipped"
    except Exception as e:
        pool_note = f"pool unavailable ({type(e).__name__}: {e}); " \
                    "pooled lane skipped"
    finally:
        for s in SWITCHES:
            os.environ.pop(s, None)
        try:
            hostlane.resolver().attach_pool(None, None)
            if pool is not None:
                pool.stop()
        except Exception:
            pass

    v_ref, m_ref = lanes["inline"]
    if not (v_ref == int(5)).sum() == 0:  # Verdict.HOST residue
        print("host_parity_smoke: inline lane left HOST cells unresolved",
              file=sys.stderr)
        return 1
    for name, (v, m) in lanes.items():
        if name == "inline":
            continue
        if not np.array_equal(v_ref, v):
            diff = np.argwhere(v_ref != v)
            print(f"host_parity_smoke: {name} verdict DIVERGENCE at "
                  f"{len(diff)} cells, first {diff[:5].tolist()}",
                  file=sys.stderr)
            return 1
        if m_ref != m:
            keys = {k for k in set(m_ref) | set(m)
                    if m_ref.get(k) != m.get(k)}
            print(f"host_parity_smoke: {name} message DIVERGENCE at "
                  f"{sorted(keys)[:5]}", file=sys.stderr)
            return 1
    if memo_stats["hits"] == 0:
        print("host_parity_smoke: memoized lane never hit the memo",
              file=sys.stderr)
        return 1

    print(f"host_parity_smoke: OK ({len(docs)} rows x {v_ref.shape[1]} "
          f"rules, lanes: {', '.join(lanes)}; memo hits "
          f"{memo_stats['hits']}; {pool_note})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
