"""Observability-plane smoke for CI (deploy/ci_lint.sh).

Four gates over the fleet-observability plane (PR 8):

1. **Trace continuity** — one stream-client admission yields a single
   trace id covering client enqueue, stream ingest, flush (or late
   join), device dispatch/compile, and host resolve, over every
   available stream transport (grpc is skipped gracefully when not
   importable).
2. **Top-K overflow** — with ``KTPU_ATTRIB_TOP_K`` shrunk below the
   pair count, overflow pairs fold into the ``__other__`` series while
   exact totals stay tracked, and ``/debug/policies`` reports both.
3. **Watchdog flip** — an injected stall (a tiny ``KTPU_SLO_BUDGET_S``)
   flips ``/healthz`` to ``degraded`` with burn rates >= threshold, and
   restoring the budget clears it.
4. **Kill-switch parity** — verdicts are bit-identical with
   ``KTPU_TRACE=0``, ``KTPU_SLO=0``, ``KTPU_ATTRIB=0`` and
   ``KTPU_PROPAGATE=0`` against the all-on defaults.

Fast by construction: one policy, a few dozen admissions, CPU backend.
Exit 0 = OK, 1 = any gate failed.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "no-latest"},
    "spec": {"validationFailureAction": "enforce", "rules": [{
        "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"message": "m", "pattern": {
            "spec": {"containers": [{"image": "!*:latest"}]}}},
    }]},
}

# the stages one stream admission's shared trace id must cover; each
# tuple lists alternates for the same pipeline boundary
CONTINUITY_STAGES = (
    ("client_enqueue",),
    ("client_service",),
    ("stream_ingest",),
    ("coalesce_wait", "late_join"),
    ("device_dispatch", "xla_compile"),
    ("host_resolve",),
)


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default"},
            "spec": {"containers": [{"name": "c",
                                     "image": ("nginx:latest" if i % 5 == 0
                                               else f"nginx:1.{i}")}]}}


def _review(resource, uid):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": uid, "kind": {"kind": "Pod"},
                        "namespace": "default", "operation": "CREATE",
                        "object": resource}}


def _stack(continuous=True):
    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.runtime.batch import AdmissionBatcher
    from kyverno_tpu.runtime.client import FakeCluster
    from kyverno_tpu.runtime.policycache import PolicyCache
    from kyverno_tpu.runtime.webhook import WebhookServer

    cache = PolicyCache()
    cache.add(load_policy(POLICY))
    batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                               dispatch_cost_init_s=0.0,
                               oracle_cost_init_s=1.0,
                               cold_flush_fallback=False,
                               result_cache_ttl_s=0.0,
                               continuous=continuous)
    server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                           admission_batcher=batcher)
    return cache, batcher, server


def _transports():
    out = ["socket"]
    try:
        import grpc  # noqa: F401

        out.append("grpc")
    except Exception:
        pass
    return out


def gate_trace_continuity() -> list[str]:
    """One admission per transport: a single trace id must cover the
    client AND server halves of the pipeline."""
    from kyverno_tpu.runtime import tracing
    from kyverno_tpu.runtime.stream_server import StreamClient, StreamServer

    failures = []
    for transport in _transports():
        cache, batcher, server = _stack(continuous=True)
        ss = StreamServer(server, batcher, cache,
                          transport=transport).start()
        cl = StreamClient(ss.port, transport=transport)
        rec = tracing.recorder()
        rec.clear()
        try:
            tr = rec.start("client_admission", transport=transport)
            tok = tracing.bind(tr)
            try:
                out = cl.admit_json(_review(_pod(1), "uid-1"), timeout=30.0)
            finally:
                tracing.unbind(tok)
                rec.finish(tr)
            if not out.get("response", {}).get("allowed"):
                failures.append(f"continuity[{transport}]: clean pod "
                                f"denied")
                continue
            tid = tr.trace_id
            names: set = set()
            for t in rec.traces(64):
                if t.trace_id == tid:
                    names |= t.stage_names()
            for alternates in CONTINUITY_STAGES:
                if not any(a in names for a in alternates):
                    failures.append(
                        f"continuity[{transport}]: trace {tid} missing "
                        f"{'|'.join(alternates)} (has {sorted(names)})")
        finally:
            cl.close()
            ss.stop()
            batcher.stop()
    return failures


def gate_topk_overflow() -> list[str]:
    """With top-K=2 and 4 distinct policies, two pairs own labelled
    series and the rest fold into __other__ — while exact totals stay
    tracked for all four."""
    from kyverno_tpu.runtime import metrics as metrics_mod
    from kyverno_tpu.runtime import obs_http

    failures = []
    st = metrics_mod.attrib_state()
    st.reset()
    os.environ["KTPU_ATTRIB_TOP_K"] = "2"
    try:
        reg = metrics_mod.registry()
        for p in ("pa", "pb", "pc", "pd"):
            metrics_mod.record_policy_verdicts(
                reg, [(p, "r", "PASS", 5)], lane="flush", namespace="ns")
        snap = metrics_mod.attribution_snapshot()
        if snap["labelled_pairs"] != 2:
            failures.append(f"topk: labelled_pairs {snap['labelled_pairs']}"
                            f" != 2")
        if snap["tracked_pairs"] != 4:
            failures.append(f"topk: tracked_pairs {snap['tracked_pairs']}"
                            f" != 4")
        if snap["other_cells"] != 10:
            failures.append(f"topk: other_cells {snap['other_cells']} != 10")
        other = reg.counter_value(
            "kyverno_policy_verdicts_total",
            {"policy": "__other__", "rule": "__other__",
             "verdict": "PASS", "lane": "flush"})
        if other != 10:
            failures.append(f"topk: __other__ series {other} != 10")
        if len(snap["overflow"]) != 2:
            failures.append(f"topk: overflow tail has "
                            f"{len(snap['overflow'])} rows, wanted 2")
        status, body, _ = obs_http.handle_obs_get("/debug/policies")
        if status != 200:
            failures.append("topk: /debug/policies not 200")
        else:
            payload = json.loads(body)
            if payload.get("labelled_pairs") != 2 or \
                    not payload.get("attrib_enabled"):
                failures.append(f"topk: /debug/policies payload wrong: "
                                f"{ {k: payload.get(k) for k in ('labelled_pairs', 'attrib_enabled')} }")
    finally:
        os.environ.pop("KTPU_ATTRIB_TOP_K", None)
        st.reset()
    return failures


def gate_watchdog_flip() -> list[str]:
    """Observations past a shrunken budget flip /healthz to degraded;
    restoring the budget (and clearing samples) restores ok."""
    from kyverno_tpu.runtime import obs_http
    from kyverno_tpu.runtime.slo import watchdog

    failures = []
    w = watchdog()
    w.clear()
    for _ in range(16):
        w.observe(0.005)                       # 5ms "admissions"
    os.environ["KTPU_SLO_BUDGET_S"] = "0.001"  # 1ms budget -> burn 5x
    try:
        status, body, _ = obs_http.handle_obs_get("/healthz")
        health = json.loads(body)
        if health.get("status") != "degraded":
            failures.append(f"watchdog: status {health.get('status')!r} "
                            f"under injected stall, wanted degraded")
        slo = health.get("slo", {})
        if not slo.get("degraded"):
            failures.append("watchdog: slo.degraded false under stall")
        br = slo.get("burn_rate", {})
        if not (br.get("short", 0) >= br.get("threshold", 1.0)):
            failures.append(f"watchdog: short burn {br} below threshold")
    finally:
        os.environ.pop("KTPU_SLO_BUDGET_S", None)
    w.clear()
    status, body, _ = obs_http.handle_obs_get("/healthz")
    health = json.loads(body)
    if health.get("status") != "ok":
        failures.append(f"watchdog: status {health.get('status')!r} after "
                        f"budget restore, wanted ok")
    # KTPU_SLO=0: observe() no-ops and /healthz reports disabled-ok
    os.environ["KTPU_SLO"] = "0"
    try:
        w.observe(99.0)
        status, body, _ = obs_http.handle_obs_get("/healthz")
        health = json.loads(body)
        if health.get("status") != "ok" or health["slo"].get("enabled"):
            failures.append(f"watchdog: KTPU_SLO=0 healthz "
                            f"{health.get('status')}/{health['slo']}")
    finally:
        os.environ.pop("KTPU_SLO", None)
    w.clear()
    return failures


def _burst_verdicts(env: dict) -> list:
    """Run one fixed admission burst under ``env`` overrides; returns
    the allowed bits in submission order. (Denial *messages* are not
    compared: which lane served a deny — device short-circuit vs host
    oracle — legitimately varies with flush timing and changes the
    message prose, observability lanes on or off.)"""
    from kyverno_tpu.runtime.stream_server import StreamClient, StreamServer

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        cache, batcher, server = _stack(continuous=True)
        ss = StreamServer(server, batcher, cache,
                          transport="socket").start()
        cl = StreamClient(ss.port, transport="socket")
        try:
            ids = [cl.submit_json(_review(_pod(i), f"uid-{i}"))
                   for i in range(32)]
            outs = [cl.result(i, timeout=30.0) for i in ids]
            return [o["response"]["allowed"] for o in outs]
        finally:
            cl.close()
            ss.stop()
            batcher.stop()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def gate_killswitch_parity() -> list[str]:
    """Every new lane off must reproduce the all-on verdicts bit for
    bit — the observability plane is a pure observer."""
    baseline = _burst_verdicts({})
    failures = []
    for env in ({"KTPU_TRACE": "0"}, {"KTPU_SLO": "0"},
                {"KTPU_ATTRIB": "0"}, {"KTPU_PROPAGATE": "0"},
                {"KTPU_TRACE": "0", "KTPU_SLO": "0", "KTPU_ATTRIB": "0",
                 "KTPU_PROPAGATE": "0"}):
        got = _burst_verdicts(env)
        if got != baseline:
            bad = sum(1 for a, b in zip(baseline, got) if a != b)
            failures.append(f"parity: {env} diverged on {bad}/32 verdicts")
    return failures


def main() -> int:
    failures = []
    for gate in (gate_trace_continuity, gate_topk_overflow,
                 gate_watchdog_flip, gate_killswitch_parity):
        try:
            failures.extend(gate())
        except Exception as exc:
            import traceback

            traceback.print_exc()
            failures.append(f"{gate.__name__}: {type(exc).__name__}: {exc}")
    if failures:
        for f in failures:
            print(f"obs_smoke: {f}", file=sys.stderr)
        return 1
    transports = ", ".join(_transports())
    print(f"obs_smoke: OK (trace continuity over {transports}; top-K "
          f"overflow; watchdog degraded flip + restore; kill-switch "
          f"verdict parity)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
