"""Workload-plane smoke for CI (deploy/ci_lint.sh).

Proves the replay harness and the rollout dry-run service keep their
two core promises on every run:

1. cross-leg verdict parity — one small synthesized churn trace plays
   through the webhook, stream (JSON + ROW), and background legs of a
   single serving stack; every admission leg must produce the same
   per-event verdict digest and the background leg's persisted verdict
   matrix must flag exactly the resources the admission stream denied;
2. dry-run blast radius with zero live impact — a >=10k-resource
   corpus is built by replaying a large trace through the background
   leg (the real watch machinery), then a known-tightening candidate
   dry-runs against it: the reported newly-failing set must equal an
   independently computed plant, and the scanner state fingerprint,
   the verdict matrix bytes, and the admission batcher's result-cache
   fingerprint must not move;
3. kill switch — KTPU_DRYRUN=0 must refuse the dry-run (403 on the
   HTTP surface, DryRunDisabled in-process) while a live admission
   decision stays byte-identical across the refused attempt.

Exit 0 = all hold, 1 = any divergence.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _policy(name, pattern, message):
    return {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": name},
            "spec": {"validationFailureAction": "enforce",
                     "background": True, "rules": [{
                         "name": f"{name}-r0",
                         "match": {"resources": {"kinds": ["Pod"]}},
                         "validate": {"message": message,
                                      "pattern": pattern}}]}}


def main() -> int:
    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.runtime import obs_http
    from kyverno_tpu.runtime.webhook import VALIDATING_WEBHOOK_PATH
    from kyverno_tpu.workload.dryrun import (DryRunDisabled, dry_run,
                                             set_scan_source)
    from kyverno_tpu.workload.replay import ReplayDriver, build_stack
    from kyverno_tpu.workload.trace import synthesize

    docs = [
        _policy("disallow-latest",
                {"spec": {"containers": [{"image": "!*:latest"}]}},
                "latest tag banned"),
        _policy("require-team-label",
                {"metadata": {"labels": {"team": "?*"}}},
                "team label required"),
    ]
    pols = [load_policy(d) for d in docs]

    # ---- 1. three-leg verdict parity on a small trace ----------------
    tr = synthesize(events=90, namespaces=3, name_pool=18,
                    distinct_bodies=10, seed=11)
    stack = build_stack(pols)
    drv = ReplayDriver.from_stack(stack)
    legs = {leg: drv.run(tr, leg, workers=4)
            for leg in ("webhook", "stream_json", "stream_row")}
    digests = {r["verdict_digest"] for r in legs.values()}
    if len(digests) != 1:
        print("replay_smoke: admission-leg verdict DIVERGENCE: "
              f"{ {leg: r['verdict_digest'] for leg, r in legs.items()} }",
              file=sys.stderr)
        return 1
    if legs["webhook"]["denied"] == 0:
        print("replay_smoke: all-allow trace — parity is vacuous",
              file=sys.stderr)
        return 1
    bg = drv.run(tr, "background")
    if bg["failing_resources"] != legs["webhook"]["failing_resources"]:
        print("replay_smoke: background verdict matrix disagrees with "
              "the admission stream on the failing set", file=sys.stderr)
        return 1

    # ---- 2. >=10k-row corpus + planted blast radius, quiescent -------
    big = synthesize(events=13_000, namespaces=6, distinct_bodies=48,
                     update_fraction=0.12, delete_fraction=0.02, seed=3)
    bstack = build_stack(pols)
    bdrv = ReplayDriver.from_stack(bstack)
    bdrv.run(big, "background")
    scanner = bstack["scanner"]
    batcher = bstack["batcher"]
    corpus = len(scanner._state["keys"])
    if corpus < 10_000:
        print(f"replay_smoke: corpus too small ({corpus} rows < 10k)",
              file=sys.stderr)
        return 1

    # independent plant: count live resources carrying the app-3 label
    planted = sorted(
        "/".join((k[0], k[1], k[2]))
        for k in scanner._state["keys"]
        if (scanner._state["resources"][k].get("metadata", {})
            .get("labels", {}).get("app")) == "app-3")
    candidate = _policy("freeze-app-3",
                        {"metadata": {"labels": {"app": "!app-3"}}},
                        "app-3 template frozen")

    fp_scan = scanner.state_fingerprint()
    fp_cache = batcher.cache_fingerprint()
    keys_b, cols_b, mat_b = scanner.verdict_matrix()
    report = dry_run(candidate, scanner=scanner)
    got = sorted("/".join((k, n, m)) for k, n, m in
                 [tuple(r.split("/")) for r in
                  report["newly_failing_resources"]])
    if report["newly_failing"] != len(planted) or got != planted:
        print(f"replay_smoke: blast radius mismatch — reported "
              f"{report['newly_failing']}, planted {len(planted)}",
              file=sys.stderr)
        return 1
    if report["resources_evaluated"] != corpus:
        print("replay_smoke: dry-run did not cover the corpus",
              file=sys.stderr)
        return 1
    keys_a, cols_a, mat_a = scanner.verdict_matrix()
    if (scanner.state_fingerprint() != fp_scan
            or batcher.cache_fingerprint() != fp_cache
            or keys_a != keys_b or cols_a != cols_b
            or mat_a.tobytes() != mat_b.tobytes()):
        print("replay_smoke: dry-run MOVED live state (fingerprint or "
              "verdict-matrix drift)", file=sys.stderr)
        return 1

    # ---- 3. KTPU_DRYRUN=0: refused, live decisions bit-identical -----
    review = {"apiVersion": "admission.k8s.io/v1",
              "kind": "AdmissionReview",
              "request": {"uid": "smoke-probe",
                          "kind": {"kind": "Pod"},
                          "namespace": "team-0", "operation": "CREATE",
                          "object": tr.body_of(tr.events[0])}}
    before = json.dumps(
        stack["webhook"].handle(VALIDATING_WEBHOOK_PATH, review),
        sort_keys=True)
    os.environ["KTPU_DRYRUN"] = "0"
    try:
        try:
            dry_run(candidate, scanner=scanner)
            print("replay_smoke: KTPU_DRYRUN=0 did not refuse",
                  file=sys.stderr)
            return 1
        except DryRunDisabled:
            pass
        set_scan_source(scanner)
        status, _, _ = obs_http.handle_obs_post(
            "/debug/dryrun",
            json.dumps({"policy": candidate}).encode())
        if status != 403:
            print(f"replay_smoke: /debug/dryrun returned {status} "
                  "while disabled (want 403)", file=sys.stderr)
            return 1
    finally:
        del os.environ["KTPU_DRYRUN"]
        set_scan_source(None)
    after = json.dumps(
        stack["webhook"].handle(VALIDATING_WEBHOOK_PATH, review),
        sort_keys=True)
    if before != after:
        print("replay_smoke: live admission decision drifted across a "
              "refused dry-run", file=sys.stderr)
        return 1
    if scanner.state_fingerprint() != fp_scan:
        print("replay_smoke: refused dry-run moved scan state",
              file=sys.stderr)
        return 1

    stack["batcher"].stop()
    batcher.stop()
    print(f"replay_smoke: OK (3-leg parity on {legs['webhook']['events']}"
          f" events / {legs['webhook']['denied']} denies, corpus "
          f"{corpus} rows, blast radius {report['newly_failing']} == "
          f"planted, quiescent fingerprints, kill switch exact)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
