#!/usr/bin/env bash
# CI lint gate: python hygiene (ruff, when available) + the policy IR
# static analyzer over the repo's sample policies. Fails on any
# ERROR-severity diagnostic (see ANALYSIS.md for codes/severities).
#
# Usage: deploy/ci_lint.sh [policy-paths...]   (default: tests/policies)
set -euo pipefail
cd "$(dirname "$0")/.."

rc=0

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff check"
    ruff check kyverno_tpu tests deploy bench.py || rc=1
else
    echo "== ruff not installed; skipping python hygiene pass"
fi

echo "== analyzer self-smoke (kyverno-tpu lint --self --certify)"
python -m kyverno_tpu.cli lint --self --certify --fail-on error >/dev/null || rc=1

echo "== policy static analysis (fail on ERROR diagnostics)"
python -m kyverno_tpu.cli lint --fail-on error "${@:-tests/policies}" || rc=1

echo "== feature-lane lint (KT5xx: KTPU_* switch matrix closed)"
python -m kyverno_tpu.analysis.featurelint || rc=1

# The runtime smoke chain verifies behavior that only matters on a
# build whose static gates are green; with lint already failing the
# run is red either way, so don't burn minutes confirming it.
if [ "$rc" -ne 0 ]; then
    echo "ci_lint: static analysis failed; skipping runtime smoke chain" >&2
    echo "ci_lint: FAILED" >&2
    exit "$rc"
fi

# CI_LINT_FUZZ_CASES trims the differential fuzz for callers on a test
# budget (the lint-CLI battery); real CI keeps the >=1000-case default.
echo "== certifier smoke (KT4xx corpus + detector self-test + differential fuzz)"
JAX_PLATFORMS=cpu python deploy/certify_smoke.py "${CI_LINT_FUZZ_CASES:-1000}" || rc=1

echo "== pipeline parity smoke (serial vs pipelined dataflow)"
JAX_PLATFORMS=cpu python deploy/pipeline_smoke.py || rc=1

echo "== policy-storm smoke (incremental splice parity + kill switch)"
JAX_PLATFORMS=cpu python deploy/storm_smoke.py || rc=1

echo "== host-lane parity smoke (inline vs prefetched vs memoized vs pooled)"
JAX_PLATFORMS=cpu python deploy/host_parity_smoke.py || rc=1

echo "== tracing smoke (verdict parity on/off, stage coverage, /metrics parse)"
JAX_PLATFORMS=cpu python deploy/trace_smoke.py || rc=1

echo "== streaming smoke (webhook/stream parity, KTPU_STREAM=0 parity, donation)"
JAX_PLATFORMS=cpu python deploy/stream_smoke.py || rc=1

echo "== observability smoke (trace continuity, top-K overflow, SLO flip, parity)"
JAX_PLATFORMS=cpu python deploy/obs_smoke.py || rc=1

echo "== replay smoke (3-leg trace parity, 10k dry-run blast radius, quiescence)"
JAX_PLATFORMS=cpu python deploy/replay_smoke.py || rc=1

echo "== chaos smoke (brownout degrade->act->recover, KTPU_SLO_ACTIONS=0 parity)"
JAX_PLATFORMS=cpu python deploy/chaos_smoke.py || rc=1

echo "== mesh smoke (1D/2D verdict parity, KT305 partition, kill switch)"
JAX_PLATFORMS=cpu python deploy/mesh_smoke.py || rc=1

echo "== fleet smoke (cross-replica fabric hits, churn invalidation, 1-vs-2 parity, scan takeover, kill switch)"
JAX_PLATFORMS=cpu python deploy/fleet_smoke.py || rc=1

if [ "$rc" -ne 0 ]; then
    echo "ci_lint: FAILED" >&2
else
    echo "ci_lint: OK"
fi
exit "$rc"
