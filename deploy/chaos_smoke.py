"""Degradation-loop smoke for CI (deploy/ci_lint.sh).

Proves the closed SLO loop (runtime/sloactions.py + workload/chaos.py)
keeps its two core promises on every run, with a fault small enough for
a CI lane:

1. degrade -> act -> recover — a short oracle-pool brownout trips the
   multi-window watchdog; the degradation controller must engage at
   least one ladder action, log it with enter/exit timestamps into the
   run manifest, and then stand everything down on its own: degraded
   gauge back at 0 without a restart, post-recovery verdict digest
   bit-identical to the undisturbed baseline, any episode drift covered
   by an explicitly reported shed set, and the state-seconds counter
   accounting both states;
2. kill switch — KTPU_SLO_ACTIONS=0 under the same fault must restore
   annotate-only behavior exactly: zero actions engage and even the
   episode digest matches the baseline byte-for-byte.

Exit 0 = all hold, 1 = any divergence.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from kyverno_tpu.workload.chaos import run_scenario

    failures = []

    # -- leg 1: the loop closes under a short brownout ----------------
    rep = run_scenario("oracle_brownout", events=24, delay_s=0.35,
                       workers=6, actions="1")
    for check, ok in rep["checks"].items():
        if not ok:
            failures.append(f"oracle_brownout: check {check} failed")
    if not rep["action_log"]:
        failures.append("oracle_brownout: no actions logged")
    for entry in rep["action_log"]:
        if "t" not in entry or entry["event"] not in ("enter", "exit"):
            failures.append(f"oracle_brownout: malformed log {entry}")
    slo = rep["manifest"].get("slo") or {}
    if not slo.get("action_log"):
        failures.append("oracle_brownout: manifest missing slo action log")

    # -- leg 2: KTPU_SLO_ACTIONS=0 restores annotate-only -------------
    par = run_scenario("oracle_brownout", events=24, delay_s=0.35,
                       workers=6, actions="0")
    for check in ("no_actions_engaged", "episode_digest_matches",
                  "recovery_digest_matches", "degraded_seen"):
        if not par["checks"].get(check):
            failures.append(f"killswitch: check {check} failed")

    print(json.dumps({
        "brownout": {"ok": rep["ok"], "checks": rep["checks"],
                     "shed": rep["shed"],
                     "actions": sorted({e["action"]
                                        for e in rep["action_log"]})},
        "killswitch": {"ok": par["ok"], "checks": par["checks"]},
        "failures": failures,
    }, indent=1))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
