"""2D mesh smoke for CI (deploy/ci_lint.sh).

Forces 4 virtual CPU devices and proves the PR-14 mesh contract on a
mixed-lane synthetic corpus (device pattern rules + host-lane rules):

1. geometry — ``KTPU_MESH_SHAPE=2x2`` turns :func:`make_mesh` into the
   2D ``(policy, data)`` grid, ``auto`` factors the device count, and
   with the switch unset the mesh is the historical 1D ``(data,)`` one;
2. verdict parity — the unsharded ``evaluate``, the 1D ``sharded_scan``
   and the 2D ``sharded_scan`` produce byte-identical verdict matrices
   and per-rule counts (host-lane cells oracle-resolved in all three);
3. kill switch — with ``KTPU_MESH_SHAPE`` deleted the scan reproduces
   the 1D baseline bit-for-bit;
4. partition invariants — the KT305 battery
   (analysis.check_policy_shards) is clean, and a single-policy churn
   step reassembles exactly one shard while parity holds.

Fast by construction: CPU backend, a dozen policies, a few dozen rows.
Exit 0 = parity, 1 = divergence.
"""

import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# force exactly 4 virtual devices even when the caller (e.g. the pytest
# conftest running ci_lint.sh) already pinned a different count — the
# assertions below hard-code the (2, 2) geometry
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=4").strip()
os.environ.pop("KTPU_MESH_SHAPE", None)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default",
                         "labels": {"idx": str(i)}},
            "spec": {"containers": [{"name": "c",
                                     "image": ("nginx:latest" if i % 3 == 0
                                               else f"nginx:1.{i}")}],
                     "weight": (i * 7) % 160,
                     "grace": f"{(i * 13) % 400}s"}}


def main() -> int:
    import numpy as np

    from kyverno_tpu.analysis import check_policy_shards
    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.models import Verdict
    from kyverno_tpu.models.engine import IncrementalCompiler
    from kyverno_tpu.parallel import make_mesh, mesh_from_env, sharded_scan
    from kyverno_tpu.parallel.mesh import is_2d, parse_mesh_shape

    def policy(name, pattern):
        return load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": name},
            "spec": {"validationFailureAction": "enforce", "rules": [{
                "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"message": "m", "pattern": pattern},
            }]},
        })

    lib = {}
    for i in range(5):
        lib[f"weight-{i}"] = policy(f"weight-{i}",
                                    {"spec": {"weight": f"<={40 + i * 20}"}})
        lib[f"grace-{i}"] = policy(f"grace-{i}",
                                   {"spec": {"grace": f"<{i + 1}h"}})
    lib["no-latest"] = policy(
        "no-latest", {"spec": {"containers": [{"image": "!*:latest"}]}})
    # host lane: the variable pattern escapes the device lattice
    lib["self-name"] = policy(
        "self-name",
        {"metadata": {"name": "{{request.object.metadata.name}}"}})
    docs = [_pod(i) for i in range(37)]     # ragged vs every mesh multiple

    # geometry grammar
    if parse_mesh_shape("", 4) is not None or \
            parse_mesh_shape("1d", 4) is not None:
        print("mesh_smoke: unset/'1d' must select the 1D mesh",
              file=sys.stderr)
        return 1
    if parse_mesh_shape("auto", 4) != (2, 2) or \
            parse_mesh_shape("2x2", 4) != (2, 2):
        print("mesh_smoke: auto/2x2 on 4 devices must factor to (2, 2)",
              file=sys.stderr)
        return 1

    inc = IncrementalCompiler()
    cps = inc.refresh(list(lib.values()))
    if not np.asarray(cps.tensors.rule_host_only).any():
        print("mesh_smoke: corpus lost its host-lane rule", file=sys.stderr)
        return 1
    want = np.asarray(cps.evaluate(docs))

    # 1D baseline: switch unset -> make_mesh() is the historical mesh
    if mesh_from_env() is not None:
        print("mesh_smoke: mesh_from_env must be None while the switch "
              "is unset", file=sys.stderr)
        return 1
    mesh1 = make_mesh()
    if is_2d(mesh1):
        print("mesh_smoke: default make_mesh() must stay 1D",
              file=sys.stderr)
        return 1
    v1, f1, p1 = sharded_scan(cps, docs, mesh1)
    if not np.array_equal(v1, want):
        print("mesh_smoke: 1D scan DIVERGES from unsharded evaluate",
              file=sys.stderr)
        return 1

    # 2D: env-selected geometry, sharded policy set, verdict parity
    os.environ["KTPU_MESH_SHAPE"] = "2x2"
    try:
        mesh2 = mesh_from_env()
        if mesh2 is None or not is_2d(mesh2) or \
                tuple(mesh2.devices.shape) != (2, 2):
            print("mesh_smoke: KTPU_MESH_SHAPE=2x2 did not build the "
                  "(2, 2) mesh", file=sys.stderr)
            return 1
        sps = inc.refresh_sharded(list(lib.values()), 2)
        v2, f2, p2 = sharded_scan(sps, docs, mesh2)
    finally:
        del os.environ["KTPU_MESH_SHAPE"]
    if not (np.array_equal(v2, want) and v2.dtype == v1.dtype):
        print("mesh_smoke: 2D scan DIVERGES from unsharded evaluate",
              file=sys.stderr)
        return 1
    if not (np.array_equal(f1, f2) and np.array_equal(p1, p2)):
        print("mesh_smoke: 2D per-rule counts DIVERGE from 1D",
              file=sys.stderr)
        return 1
    if (v2 == Verdict.HOST).any():
        print("mesh_smoke: 2D scan left unresolved HOST cells",
              file=sys.stderr)
        return 1

    # partition invariants (KT305) + footprint sanity
    diags = check_policy_shards(
        sps.full.tensors,
        [(sh.cps.tensors, sh.col_map) for sh in sps.shards])
    if diags:
        print(f"mesh_smoke: KT305 battery found {len(diags)} violations "
              f"(first: {diags[0].code} {diags[0].message})",
              file=sys.stderr)
        return 1
    counts = sps.shard_rule_counts()
    if sum(counts.values()) != sps.full.tensors.n_rules_live or \
            max(counts.values()) >= sps.full.tensors.n_rules_live:
        print(f"mesh_smoke: shard rule counts {counts} do not partition "
              f"{sps.full.tensors.n_rules_live} live rules",
              file=sys.stderr)
        return 1

    # churn: replacing one policy must reassemble exactly one shard and
    # keep parity
    lib["no-latest"] = policy(
        "no-latest",
        {"spec": {"containers": [{"image": "!*:latest", "name": "c?*"}]}})
    sps = inc.refresh_sharded(list(lib.values()), 2, sharded=sps)
    if sps.last_refresh["shards_reassembled"] != 1:
        print(f"mesh_smoke: churn reassembled "
              f"{sps.last_refresh['shards_reassembled']} shards, want 1",
              file=sys.stderr)
        return 1
    want2 = np.asarray(sps.full.evaluate(docs))
    os.environ["KTPU_MESH_SHAPE"] = "2x2"
    try:
        v3, _, _ = sharded_scan(sps, docs, mesh_from_env())
    finally:
        del os.environ["KTPU_MESH_SHAPE"]
    if not np.array_equal(v3, want2):
        print("mesh_smoke: post-churn 2D scan DIVERGES", file=sys.stderr)
        return 1

    # kill switch: with the env var gone the scan is the 1D baseline
    # bit-for-bit (same mesh geometry, same bytes)
    killed = make_mesh()
    if is_2d(killed):
        print("mesh_smoke: kill switch did not restore the 1D mesh",
              file=sys.stderr)
        return 1
    vk, fk, pk = sharded_scan(cps, docs, killed)
    if not (np.array_equal(vk, v1) and vk.dtype == v1.dtype
            and np.array_equal(fk, f1) and np.array_equal(pk, p1)):
        print("mesh_smoke: kill-switch scan is not the 1D baseline "
              "bit-for-bit", file=sys.stderr)
        return 1

    print(f"mesh_smoke: OK ({len(docs)} rows x {len(lib)} policies, "
          f"shards {counts}, 1D/2D/unsharded verdicts identical, "
          "KT305 clean, churn reassembled 1 shard, kill switch exact)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
