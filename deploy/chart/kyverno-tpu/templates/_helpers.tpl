{{- define "kyverno-tpu.name" -}}
{{ .Values.nameOverride | default .Chart.Name }}
{{- end -}}

{{- define "kyverno-tpu.fullname" -}}
{{ .Values.fullnameOverride | default (include "kyverno-tpu.name" .) }}
{{- end -}}

{{- define "kyverno-tpu.namespace" -}}
{{ .Values.namespace | default .Release.Namespace }}
{{- end -}}

{{- define "kyverno-tpu.serviceAccountName" -}}
{{ .Values.serviceAccount.name | default (include "kyverno-tpu.fullname" .) }}
{{- end -}}

{{- define "kyverno-tpu.labels" -}}
app: {{ include "kyverno-tpu.fullname" . }}
app.kubernetes.io/name: {{ include "kyverno-tpu.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end -}}

{{- define "kyverno-tpu.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag | default .Chart.AppVersion }}
{{- end -}}

{{- define "kyverno-tpu.initImage" -}}
{{ .Values.initImage.repository | default .Values.image.repository }}:{{ .Values.initImage.tag | default (.Values.image.tag | default .Chart.AppVersion) }}
{{- end -}}
