"""Pipeline-parity smoke for CI (deploy/ci_lint.sh).

Runs the same resource set through the serial dataflow
(KTPU_FLATTEN_PIPELINE=0: plain flatten, blocking dispatch) and the
pipelined one (row memo, splice, async double-buffered dispatch) and
fails on any verdict difference. Fast by construction: one small policy
set, a few hundred rows, CPU backend — the point is the diff, not the
throughput. Exit 0 = parity, 1 = divergence.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default",
                         "labels": {"idx": str(i)}},
            "spec": {"containers": [{"name": "c",
                                     "image": ("nginx:latest" if i % 3 == 0
                                               else f"nginx:1.{i}")}],
                     "weight": (i * 7) % 160,
                     "frac": i + 0.5}}


def main() -> int:
    import numpy as np

    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.models import CompiledPolicySet

    policies = [load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "m", "pattern": pattern},
        }]},
    }) for name, pattern in (
        ("no-latest", {"spec": {"containers": [{"image": "!*:latest"}]}}),
        ("weight-cap", {"spec": {"weight": "<=100"}}),
    )]
    cps = CompiledPolicySet(policies)
    docs = [_pod(i) for i in range(384)]

    os.environ["KTPU_FLATTEN_PIPELINE"] = "0"
    v_serial = np.asarray(cps.evaluate_pipelined(docs, chunk=128))
    os.environ["KTPU_FLATTEN_PIPELINE"] = "1"
    v_pipe = np.asarray(cps.evaluate_pipelined(docs, chunk=128))

    if not np.array_equal(v_serial, v_pipe):
        diff = np.argwhere(v_serial != v_pipe)
        print(f"pipeline_smoke: DIVERGENCE at {len(diff)} cells, "
              f"first {diff[:5].tolist()}", file=sys.stderr)
        return 1

    # memo-splice lane: rows flattened once, spliced from the memo the
    # second time, must score identically to the fresh flatten
    from kyverno_tpu.models.flatten import (
        split_packed_rows,
        splice_packed_rows,
    )

    rows = split_packed_rows(cps.flatten_packed(docs[:64]))
    v_spliced = np.asarray(cps.evaluate_device(splice_packed_rows(rows)))
    v_fresh = np.asarray(cps.evaluate_device(cps.flatten_packed(docs[:64])))
    if not np.array_equal(v_spliced, v_fresh):
        print("pipeline_smoke: memo splice DIVERGENCE", file=sys.stderr)
        return 1

    print(f"pipeline_smoke: OK ({len(docs)} rows x "
          f"{v_pipe.shape[1]} rules, serial == pipelined == spliced)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
