"""Cross-layer certification smoke for CI (deploy/ci_lint.sh).

Gates, in order:

1. **Corpus certification** — every rule in tests/policies either
   certifies clean, is host-escalated, or is explicitly counted
   KT404-incomplete. Zero KT401 divergences allowed.
2. **Detector self-test** — seeded corruptions of assembled tensors
   (flipped group negation, flipped boolean literal, rewired alt) MUST
   each produce a KT401; a certifier that can't see planted divergence
   is vacuous.
3. **Discharge probe** — a hand-escalated device-decidable rule MUST
   produce KT402 (the escalation is provably wasted), and a genuinely
   host-only rule must NOT.
4. **Differential fuzz** — >=1000 random policy x resource cases scored
   through the real device kernel and the CPU oracle (plus the
   pipelined and streaming legs): zero unexplained divergences.

Exit 0 = all gates hold, 1 = any failed.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CORPUS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "policies")


def _build(path):
    from kyverno_tpu.api.load import load_policies_from_path
    from kyverno_tpu.models.compiler import (TensorDictionary,
                                             assemble_tensors,
                                             compile_segment)
    from kyverno_tpu.models.ir import compile_rule_ir

    pols = load_policies_from_path(path)
    p = pols[0]
    vrules = [r for r in p.spec.rules if r.has_validate()]
    irs = [compile_rule_ir(p, r, i) for i, r in enumerate(vrules)]
    d = TensorDictionary()
    seg = compile_segment(irs, d, name=p.name)
    return p, irs, assemble_tensors([seg], d)


def gate_corpus() -> list[str]:
    from kyverno_tpu.analysis.certify import certify_policies
    from kyverno_tpu.api.load import load_policies_from_path

    failures = []
    policies = load_policies_from_path(CORPUS)
    if not policies:
        return [f"no policies found under {CORPUS}"]
    res = certify_policies(policies)
    counts = res.counts()
    if res.divergences:
        failures.extend(
            f"corpus KT401: {d.format()}" for d in res.divergences[:5])
    undischarged = sum(1 for s in res.statuses.values()
                       if s not in ("certified", "incomplete", "host"))
    if undischarged:
        failures.append(
            f"{undischarged} rule(s) neither certified, host, nor "
            f"KT404-counted: {counts}")
    if not counts.get("certified"):
        failures.append(f"no rule certified at all: {counts}")
    print(f"certify_smoke: corpus {counts}, "
          f"{res.states_checked} states, "
          f"{res.escalation_cells} escalation cells, "
          f"{sum(1 for d in res.diagnostics if d.code == 'KT404')} "
          f"KT404, {sum(1 for d in res.diagnostics if d.code == 'KT403')} "
          f"KT403")
    return failures


def gate_detector() -> list[str]:
    import numpy as np

    from kyverno_tpu.analysis.certify import certify_tensors

    failures = []

    # flipped aux-group negation on the deny-constant sample
    _, _, t = _build(os.path.join(CORPUS, "sample_deny_constant.yaml"))
    t.axg_negate = np.array(t.axg_negate).copy()
    t.axg_negate[0] = not t.axg_negate[0]
    r = certify_tensors(t)
    if not any(d.code == "KT401" for d in r.diagnostics):
        failures.append("planted group-negate corruption not detected")

    # flipped boolean literal on the clean sample's runAsNonRoot rule
    _, _, t = _build(os.path.join(CORPUS, "sample_clean.yaml"))
    bools = np.array(t.chk_bool).copy()
    ops = np.array(t.chk_op)
    flipped = False
    from kyverno_tpu.models.ir import CheckOp
    for i in range(len(ops)):
        if int(ops[i]) == int(CheckOp.BOOL_EQ):
            bools[i] = not bools[i]
            flipped = True
            break
    t.chk_bool = bools
    r = certify_tensors(t)
    if not flipped:
        failures.append("no BOOL_EQ row found to corrupt")
    elif not any(d.code == "KT401" for d in r.diagnostics):
        failures.append("planted boolean-literal corruption not detected")

    # rewired alt -> wrong rule row (structural)
    _, _, t = _build(os.path.join(CORPUS, "sample_clean.yaml"))
    t.alt_rule = np.array(t.alt_rule).copy()
    t.alt_rule[0] = (int(t.alt_rule[0]) + 1) % max(2, t.n_rules_logical)
    r = certify_tensors(t)
    if not any(d.code == "KT401" for d in r.diagnostics):
        failures.append("planted alt rewiring not detected")
    return failures


def gate_discharge() -> list[str]:
    from kyverno_tpu.analysis.certify import certify_tensors
    from kyverno_tpu.api.load import load_policies_from_path
    from kyverno_tpu.models.compiler import (TensorDictionary,
                                             assemble_tensors,
                                             compile_segment)
    from kyverno_tpu.models.ir import compile_rule_ir

    failures = []
    # device-decidable rule force-escalated -> must flag KT402
    pols = load_policies_from_path(
        os.path.join(CORPUS, "sample_deny_constant.yaml"))
    p = pols[0]
    vrules = [r for r in p.spec.rules if r.has_validate()]
    irs = [compile_rule_ir(p, r, i) for i, r in enumerate(vrules)]
    irs[0].host_only = True
    irs[0].host_reason = "smoke: forced escalation"
    d = TensorDictionary()
    t = assemble_tensors([compile_segment(irs, d, name=p.name)], d)
    r = certify_tensors(t)
    if not any(x.code == "KT402" for x in r.diagnostics):
        failures.append("forced escalation not flagged KT402")

    # genuinely host rule (variables) -> must NOT flag KT402
    pols = load_policies_from_path(
        os.path.join(CORPUS, "sample_host_variable.yaml"))
    p = pols[0]
    vrules = [r for r in p.spec.rules if r.has_validate()]
    irs = [compile_rule_ir(p, r, i) for i, r in enumerate(vrules)]
    d = TensorDictionary()
    t = assemble_tensors([compile_segment(irs, d, name=p.name)], d)
    r = certify_tensors(t)
    if any(x.code == "KT402" for x in r.diagnostics):
        failures.append("genuinely host rule wrongly flagged KT402")
    return failures


def gate_fuzz(cases: int = 1000) -> list[str]:
    from kyverno_tpu.analysis.difffuzz import run_fuzz

    report = run_fuzz(cases=cases)
    print(f"certify_smoke: fuzz {report.cases} cases, "
          f"{report.device_cells} device cells, "
          f"{report.escalated_cells} escalated, "
          f"{report.messages_checked} messages, "
          f"{report.stream_rows} stream rows")
    if report.cases < cases:
        return [f"fuzz stopped at {report.cases}/{cases} cases"]
    return [d.format() for d in report.diagnostics()[:5]]


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    cases = int(argv[0]) if argv else 1000
    failures = []
    failures += gate_corpus()
    failures += gate_detector()
    failures += gate_discharge()
    failures += gate_fuzz(cases)
    if failures:
        print("certify_smoke: FAILED")
        for f in failures[:20]:
            print("  -", f)
        return 1
    print("certify_smoke: OK (corpus certified, planted corruptions "
          "detected, discharge probe sound, fuzz parity holds)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
