"""Policy-update-storm smoke for CI (deploy/ci_lint.sh).

Drives a 4-policy set through an update storm on the incremental
compiler and fails on any divergence from the from-scratch compile:

1. splice parity — after each single-policy update the segmented
   assembly (only the touched segment recompiled, rebased offsets,
   pow2 rule bucket) must score bit-identically to a monolithic
   ``CompiledPolicySet`` of the same policies;
2. memo survival — flatten rows memoized before the storm must
   epoch-refresh and splice to the same verdicts as fresh flattens;
3. kill switch — ``KTPU_INCREMENTAL=0`` must restore the legacy
   monolithic path exactly (same fingerprint, same verdicts).

Fast by construction: CPU backend, 4 policies, a handful of rows.
Exit 0 = parity, 1 = divergence.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default",
                         "labels": {"idx": str(i)}},
            "spec": {"containers": [{"name": "c",
                                     "image": ("nginx:latest" if i % 3 == 0
                                               else f"nginx:1.{i}")}],
                     "weight": (i * 7) % 160,
                     "grace": f"{(i * 13) % 400}s"}}


def main() -> int:
    import numpy as np

    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.models import CompiledPolicySet
    from kyverno_tpu.models.engine import IncrementalCompiler
    from kyverno_tpu.models.flatten import (
        MemoRow,
        refresh_packed_row,
        splice_packed_rows,
        split_packed_rows,
    )

    def policy(name, pattern):
        return load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": name},
            "spec": {"validationFailureAction": "enforce", "rules": [{
                "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"message": "m", "pattern": pattern},
            }]},
        })

    lib = {
        "no-latest": policy("no-latest",
                            {"spec": {"containers": [{"image": "!*:latest"}]}}),
        "weight-cap": policy("weight-cap", {"spec": {"weight": "<=100"}}),
        "grace-cap": policy("grace-cap", {"spec": {"grace": "<1h"}}),
        "named": policy("named", {"metadata": {"name": "pod-?*"}}),
    }
    docs = [_pod(i) for i in range(48)]
    inc = IncrementalCompiler()
    cps0 = inc.refresh(list(lib.values()))
    memos = [MemoRow(row=r, n_paths=cps0.tensors.n_paths,
                     epoch=cps0.tensors.dict_epoch)
             for r in split_packed_rows(cps0.flatten_packed(docs))]

    # the storm: three single-policy updates, each appending paths
    storm = [
        ("weight-cap", {"spec": {"weight": "<=90",
                                 "tier": {"class": "?*"}}}),
        ("named", {"metadata": {"annotations": {"team": "?*"}}}),
        ("no-latest", {"spec": {"containers": [{"image": "!*:latest",
                                                "name": "c?*"}]}}),
    ]
    for step, (name, pattern) in enumerate(storm):
        lib[name] = policy(name, pattern)
        policies = list(lib.values())
        cps = inc.refresh(policies)
        if inc.last_refresh["recompiled"] != 1:
            print(f"storm_smoke: step {step} recompiled "
                  f"{inc.last_refresh['recompiled']} segments, want 1",
                  file=sys.stderr)
            return 1
        fresh = CompiledPolicySet(policies)
        want = np.asarray(fresh.evaluate_device(fresh.flatten_packed(docs)))
        got = np.asarray(cps.evaluate_device(cps.flatten_packed(docs)))
        if not np.array_equal(got, want):
            print(f"storm_smoke: splice DIVERGENCE at step {step}",
                  file=sys.stderr)
            return 1

        survived = 0
        refreshed = []
        for m, d in zip(memos, docs):
            m2, _ext = refresh_packed_row(m, d, cps.tensors)
            if m2 is None:
                print(f"storm_smoke: memo row lost at step {step}",
                      file=sys.stderr)
                return 1
            survived += 1
            refreshed.append(m2)
        memos = refreshed
        spliced = np.asarray(cps.evaluate_device(
            splice_packed_rows([m.row for m in memos])))
        if not np.array_equal(spliced, want):
            print(f"storm_smoke: memo-splice DIVERGENCE at step {step}",
                  file=sys.stderr)
            return 1

    # kill switch: the legacy monolithic path, bit for bit
    os.environ["KTPU_INCREMENTAL"] = "0"
    try:
        from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

        cache = PolicyCache()
        for p in lib.values():
            cache.add(p)
        legacy = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod",
                                "default")
        t = legacy.tensors
        ref = CompiledPolicySet(legacy.policies)
        if t.dict_base is not None or t.fingerprint != ref.tensors.fingerprint:
            print("storm_smoke: kill switch did not restore the "
                  "monolithic compile", file=sys.stderr)
            return 1
        got = np.asarray(legacy.evaluate_device(legacy.flatten_packed(docs)))
        want = np.asarray(ref.evaluate_device(ref.flatten_packed(docs)))
        if not np.array_equal(got, want):
            print("storm_smoke: kill-switch verdict DIVERGENCE",
                  file=sys.stderr)
            return 1
    finally:
        del os.environ["KTPU_INCREMENTAL"]

    print(f"storm_smoke: OK ({len(docs)} rows x {len(lib)} policies, "
          f"{len(storm)} single-segment updates, memo survival "
          f"{len(memos)}/{len(docs)}, kill switch exact)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
