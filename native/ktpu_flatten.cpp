// ktpu_flatten: resource JSON -> leaf slot tensors, the native twin of
// kyverno_tpu/models/flatten.py (same layout, byte-for-byte).
//
// The reference engine has no native code (SURVEY.md header); this library
// is the new host-side component the north star calls for: admission
// payloads arrive as JSON bytes, and turning them into device tensors is
// the end-to-end bottleneck of the TPU path (bench.py flatten_s). It
// parses JSON directly (no Python dict intermediary), enumerates the
// compiled path dictionary against each document, interns the string
// dictionary, and decomposes numbers/quantities into exact i64 micro-units
// -- mirroring models/flatten.py semantics including phantom slots,
// prefix-presence masks, host-lane flags, and Go-style float
// stringification (utils/gofmt.py).
//
// C ABI only (consumed via ctypes; pybind11 is not in the image).

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>
#include <charconv>
#include <deque>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

constexpr char SEP = '\x1f';
constexpr int64_t NUM_SCALE_POW10 = 6;          // micro-units
constexpr int64_t NUM_MAX = int64_t(1) << 62;

// type tags (models/flatten.py)
enum : int8_t { T_ABSENT = 0, T_NULL, T_BOOL, T_NUM, T_STR, T_OBJ, T_LIST };

// ------------------------------------------------------------------ JSON

struct Value {
    enum Type : uint8_t { Null, Bool, Num, Str, Obj, Arr } t = Null;
    bool b = false;
    std::string_view raw;                       // Num: literal token text
    std::string str;                            // Str: decoded text
    std::vector<std::pair<std::string, Value*>> obj;
    std::vector<Value*> arr;
};

struct Parser {
    const char* p;
    const char* end;
    std::deque<Value>* arena;
    bool ok = true;

    Value* alloc() { arena->emplace_back(); return &arena->back(); }

    void skip_ws() {
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
    }

    bool lit(const char* s, size_t n) {
        if (size_t(end - p) < n || memcmp(p, s, n) != 0) return false;
        p += n;
        return true;
    }

    Value* parse() {
        skip_ws();
        if (p >= end) { ok = false; return nullptr; }
        switch (*p) {
            case '{': return parse_obj();
            case '[': return parse_arr();
            case '"': return parse_str();
            case 't': { Value* v = alloc(); v->t = Value::Bool; v->b = true;
                        if (!lit("true", 4)) ok = false; return v; }
            case 'f': { Value* v = alloc(); v->t = Value::Bool; v->b = false;
                        if (!lit("false", 5)) ok = false; return v; }
            case 'n': { Value* v = alloc(); v->t = Value::Null;
                        if (!lit("null", 4)) ok = false; return v; }
            default:  return parse_num();
        }
    }

    Value* parse_obj() {
        Value* v = alloc(); v->t = Value::Obj;
        ++p;  // '{'
        skip_ws();
        if (p < end && *p == '}') { ++p; return v; }
        while (p < end) {
            skip_ws();
            if (p >= end || *p != '"') { ok = false; return v; }
            Value* key = parse_str();
            skip_ws();
            if (p >= end || *p != ':') { ok = false; return v; }
            ++p;
            Value* val = parse();
            if (!ok) return v;
            v->obj.emplace_back(std::move(key->str), val);
            skip_ws();
            if (p < end && *p == ',') { ++p; continue; }
            if (p < end && *p == '}') { ++p; return v; }
            ok = false; return v;
        }
        ok = false; return v;
    }

    Value* parse_arr() {
        Value* v = alloc(); v->t = Value::Arr;
        ++p;  // '['
        skip_ws();
        if (p < end && *p == ']') { ++p; return v; }
        while (p < end) {
            Value* el = parse();
            if (!ok) return v;
            v->arr.push_back(el);
            skip_ws();
            if (p < end && *p == ',') { ++p; continue; }
            if (p < end && *p == ']') { ++p; return v; }
            ok = false; return v;
        }
        ok = false; return v;
    }

    Value* parse_str() {
        Value* v = alloc(); v->t = Value::Str;
        ++p;  // '"'
        std::string& out = v->str;
        while (p < end && *p != '"') {
            if (*p == '\\') {
                ++p;
                if (p >= end) { ok = false; return v; }
                switch (*p) {
                    case '"': out += '"'; break;
                    case '\\': out += '\\'; break;
                    case '/': out += '/'; break;
                    case 'b': out += '\b'; break;
                    case 'f': out += '\f'; break;
                    case 'n': out += '\n'; break;
                    case 'r': out += '\r'; break;
                    case 't': out += '\t'; break;
                    case 'u': {
                        if (end - p < 5) { ok = false; return v; }
                        unsigned cp = 0;
                        for (int i = 1; i <= 4; ++i) {
                            char c = p[i];
                            cp <<= 4;
                            if (c >= '0' && c <= '9') cp |= unsigned(c - '0');
                            else if (c >= 'a' && c <= 'f') cp |= unsigned(c - 'a' + 10);
                            else if (c >= 'A' && c <= 'F') cp |= unsigned(c - 'A' + 10);
                            else { ok = false; return v; }
                        }
                        p += 4;
                        // surrogate pairs
                        if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 7 &&
                            p[1] == '\\' && p[2] == 'u') {
                            unsigned lo = 0;
                            bool lo_ok = true;
                            for (int i = 3; i <= 6; ++i) {
                                char c = p[i];
                                lo <<= 4;
                                if (c >= '0' && c <= '9') lo |= unsigned(c - '0');
                                else if (c >= 'a' && c <= 'f') lo |= unsigned(c - 'a' + 10);
                                else if (c >= 'A' && c <= 'F') lo |= unsigned(c - 'A' + 10);
                                else { lo_ok = false; break; }
                            }
                            if (lo_ok && lo >= 0xDC00 && lo <= 0xDFFF) {
                                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                                p += 6;
                            }
                        }
                        // utf-8 encode
                        if (cp < 0x80) out += char(cp);
                        else if (cp < 0x800) {
                            out += char(0xC0 | (cp >> 6));
                            out += char(0x80 | (cp & 0x3F));
                        } else if (cp < 0x10000) {
                            out += char(0xE0 | (cp >> 12));
                            out += char(0x80 | ((cp >> 6) & 0x3F));
                            out += char(0x80 | (cp & 0x3F));
                        } else {
                            out += char(0xF0 | (cp >> 18));
                            out += char(0x80 | ((cp >> 12) & 0x3F));
                            out += char(0x80 | ((cp >> 6) & 0x3F));
                            out += char(0x80 | (cp & 0x3F));
                        }
                        break;
                    }
                    default: ok = false; return v;
                }
                ++p;
            } else {
                out += *p++;
            }
        }
        if (p >= end) { ok = false; return v; }
        ++p;  // closing '"'
        return v;
    }

    Value* parse_num() {
        Value* v = alloc(); v->t = Value::Num;
        const char* start = p;
        if (p < end && (*p == '-' || *p == '+')) ++p;
        while (p < end && ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' ||
                           *p == 'E' || *p == '+' || *p == '-')) ++p;
        if (p == start) { ok = false; return v; }
        v->raw = std::string_view(start, size_t(p - start));
        return v;
    }
};

const Value* obj_get(const Value* v, std::string_view key) {
    if (v == nullptr || v->t != Value::Obj) return nullptr;
    for (const auto& kv : v->obj)
        if (kv.first == key) return kv.second;
    return nullptr;
}

// ------------------------------------------------------------ quantities

// Exact micro-unit decomposition of a quantity token (utils/quantity.py +
// models/ir.py quantity_to_micro). Returns false when not a quantity or
// not exactly representable.
bool quantity_to_micro(std::string_view s, int64_t* out) {
    // trim
    while (!s.empty() && (s.front() == ' ' || s.front() == '\t')) s.remove_prefix(1);
    while (!s.empty() && (s.back() == ' ' || s.back() == '\t')) s.remove_suffix(1);
    if (s.empty()) return false;

    size_t i = 0;
    bool neg = false;
    if (s[i] == '+' || s[i] == '-') { neg = s[i] == '-'; ++i; }

    __int128 digits = 0;
    int n_int = 0, n_frac = 0;
    bool seen_dot = false;
    int total_digits = 0;
    for (; i < s.size(); ++i) {
        char c = s[i];
        if (c >= '0' && c <= '9') {
            if (++total_digits > 36) return false;  // beyond exact range
            digits = digits * 10 + (c - '0');
            if (seen_dot) ++n_frac; else ++n_int;
        } else if (c == '.' && !seen_dot) {
            seen_dot = true;
        } else {
            break;
        }
    }
    if (n_int == 0 && n_frac == 0) return false;

    std::string_view suffix = s.substr(i);
    int pow10 = 0;
    int pow2 = 0;
    if (!suffix.empty()) {
        if (suffix == "Ki") pow2 = 10;
        else if (suffix == "Mi") pow2 = 20;
        else if (suffix == "Gi") pow2 = 30;
        else if (suffix == "Ti") pow2 = 40;
        else if (suffix == "Pi") pow2 = 50;
        else if (suffix == "Ei") pow2 = 60;
        else if (suffix == "n") pow10 = -9;
        else if (suffix == "u") pow10 = -6;
        else if (suffix == "m") pow10 = -3;
        else if (suffix == "k") pow10 = 3;
        else if (suffix == "M") pow10 = 6;
        else if (suffix == "G") pow10 = 9;
        else if (suffix == "T") pow10 = 12;
        else if (suffix == "P") pow10 = 15;
        else if (suffix == "E") pow10 = 18;
        else if (suffix[0] == 'e' || suffix[0] == 'E') {
            int exp = 0;
            bool eneg = false;
            size_t j = 1;
            if (j < suffix.size() && (suffix[j] == '+' || suffix[j] == '-')) {
                eneg = suffix[j] == '-';
                ++j;
            }
            if (j >= suffix.size()) return false;
            for (; j < suffix.size(); ++j) {
                if (suffix[j] < '0' || suffix[j] > '9') return false;
                exp = exp * 10 + (suffix[j] - '0');
                if (exp > 40) return false;
            }
            pow10 = eneg ? -exp : exp;
        } else {
            return false;
        }
    }

    // value = digits * 10^(-n_frac) * 2^pow2 * 10^pow10; micro = value*10^6
    __int128 num = digits;
    for (int k = 0; k < pow2; ++k) {
        num <<= 1;
        if (num > (__int128(1) << 100)) return false;
    }
    int scale = -n_frac + pow10 + int(NUM_SCALE_POW10);
    while (scale > 0) {
        num *= 10;
        --scale;
        if (num > (__int128(1) << 110)) return false;
    }
    while (scale < 0) {
        if (num % 10 != 0) return false;  // sub-micro precision
        num /= 10;
        ++scale;
    }
    if (num > __int128(NUM_MAX)) return false;
    *out = neg ? -int64_t(num) : int64_t(num);
    return true;
}

// Go strconv.FormatFloat(v,'E',-1,64) — shortest mantissa, E+NN exponent
// (utils/gofmt.py format_float_sci).
std::string format_float_sci(double v) {
    if (v != v) return "NaN";
    if (v == __builtin_inf()) return "+Inf";
    if (v == -__builtin_inf()) return "-Inf";
    char buf[64];
    auto res = std::to_chars(buf, buf + sizeof buf, v);  // shortest repr
    std::string shortest(buf, res.ptr);

    bool neg = false;
    std::string digits = shortest;
    if (!digits.empty() && digits[0] == '-') { neg = true; digits.erase(0, 1); }

    std::string mant_digits;
    int iexp = 0;
    auto epos = digits.find_first_of("eE");
    if (epos != std::string::npos) {
        std::string m = digits.substr(0, epos);
        iexp = atoi(digits.c_str() + epos + 1);
        auto dot = m.find('.');
        if (dot != std::string::npos) m.erase(dot, 1);
        while (m.size() > 1 && m.back() == '0') m.pop_back();
        mant_digits = m;
    } else {
        auto dot = digits.find('.');
        std::string int_part = dot == std::string::npos ? digits : digits.substr(0, dot);
        std::string frac = dot == std::string::npos ? "" : digits.substr(dot + 1);
        if (frac == "0") frac = "";
        while (!frac.empty() && frac.back() == '0') frac.pop_back();
        if (int_part == "0") {
            size_t nz = frac.find_first_not_of('0');
            if (nz == std::string::npos) return neg ? "-0E+00" : "0E+00";
            iexp = -int(nz) - 1;
            mant_digits = frac.substr(nz);
        } else {
            iexp = int(int_part.size()) - 1;
            mant_digits = int_part + frac;
            while (mant_digits.size() > 1 && mant_digits.back() == '0')
                mant_digits.pop_back();
        }
    }
    std::string out;
    if (neg) out += '-';
    out += mant_digits[0];
    if (mant_digits.size() > 1) {
        out += '.';
        out += mant_digits.substr(1);
    }
    out += 'E';
    out += iexp >= 0 ? '+' : '-';
    int a = iexp >= 0 ? iexp : -iexp;
    char eb[8];
    snprintf(eb, sizeof eb, "%02d", a);
    out += eb;
    return out;
}

// value_to_string_for_equality for a Num token: ints keep their text,
// floats format the Go way.
bool num_token_is_int(std::string_view raw) {
    for (char c : raw)
        if (c == '.' || c == 'e' || c == 'E') return false;
    return true;
}

// ------------------------------------------------------------------ ctx

struct Ctx {
    std::vector<std::vector<std::string>> paths;   // split segments
    std::unordered_map<std::string, int32_t> kinds;
    int str_len_cap = 64;
};

struct Interner {
    std::unordered_map<std::string, int32_t> index;
    std::vector<std::string> strings;

    int32_t intern(const std::string& s) {
        auto it = index.find(s);
        if (it != index.end()) return it->second;
        int32_t id = int32_t(strings.size());
        index.emplace(s, id);
        strings.push_back(s);
        return id;
    }
};

struct Outputs {
    uint16_t* mask;
    uint8_t* slot_valid;
    int8_t* type_tag;
    int32_t* str_id;
    int64_t* num_val;
    uint8_t* num_ok;
    uint8_t* bool_val;
    int32_t* elem0;
    int32_t* kind_id;
    uint8_t* host_flag;
    int P, E;
};

struct Slot {
    uint16_t mask;
    int32_t elem0;
    const Value* leaf;   // nullptr => phantom
};

void enumerate_slots(const Value* node, const std::vector<std::string>& segs,
                     size_t i, uint16_t mask, int32_t elem0,
                     std::vector<Slot>& out, int cap) {
    if (int(out.size()) > cap) return;  // overflow checked by caller
    if (i == segs.size()) {
        out.push_back({mask, elem0, node});
        return;
    }
    const std::string& seg = segs[i];
    if (seg == "*") {
        if (node == nullptr || node->t != Value::Arr) {
            out.push_back({mask, elem0, nullptr});
            return;
        }
        int32_t idx = 0;
        for (const Value* el : node->arr) {
            enumerate_slots(el, segs, i + 1, uint16_t(mask | (1u << (i + 1))),
                            elem0 < 0 ? idx : elem0, out, cap);
            ++idx;
        }
    } else {
        const Value* child = obj_get(node, seg);
        if (child == nullptr) {
            out.push_back({mask, elem0, nullptr});
            return;
        }
        enumerate_slots(child, segs, i + 1, uint16_t(mask | (1u << (i + 1))),
                        elem0, out, cap);
    }
}

}  // namespace

extern "C" {

// paths: '\n'-joined SEP-separated generalized paths
// kinds: '\n'-joined kind names (index == id, matching tensors.kind_index)
void* ktpu_create(const char* paths, const char* kinds, int str_len_cap) {
    auto* ctx = new Ctx;
    ctx->str_len_cap = str_len_cap;
    std::string_view pv(paths ? paths : "");
    size_t start = 0;
    while (start <= pv.size() && !pv.empty()) {
        size_t nl = pv.find('\n', start);
        std::string_view line = pv.substr(
            start, nl == std::string_view::npos ? pv.size() - start : nl - start);
        if (!line.empty()) {
            std::vector<std::string> segs;
            size_t s0 = 0;
            while (true) {
                size_t sp = line.find(SEP, s0);
                if (sp == std::string_view::npos) {
                    segs.emplace_back(line.substr(s0));
                    break;
                }
                segs.emplace_back(line.substr(s0, sp - s0));
                s0 = sp + 1;
            }
            ctx->paths.push_back(std::move(segs));
        }
        if (nl == std::string_view::npos) break;
        start = nl + 1;
    }
    std::string_view kv(kinds ? kinds : "");
    start = 0;
    int32_t kid = 0;
    while (start <= kv.size() && !kv.empty()) {
        size_t nl = kv.find('\n', start);
        std::string_view line = kv.substr(
            start, nl == std::string_view::npos ? kv.size() - start : nl - start);
        if (!line.empty()) ctx->kinds.emplace(std::string(line), kid++);
        if (nl == std::string_view::npos) break;
        start = nl + 1;
    }
    return ctx;
}

void ktpu_destroy(void* handle) { delete static_cast<Ctx*>(handle); }

// Flatten a batch. Arrays are laid out [B, P, E] row-major with E =
// max_slots; returns the maximum slot count actually used (<= max_slots),
// or -1 when the string dictionary capacity was exceeded (caller retries
// with a larger str_cap). Documents that fail to parse set host_flag.
int ktpu_flatten_batch(
    void* handle, const char* const* docs, const int32_t* doc_lens, int n_docs,
    int max_slots,
    uint16_t* mask, uint8_t* slot_valid, int8_t* type_tag, int32_t* str_id,
    int64_t* num_val, uint8_t* num_ok, uint8_t* bool_val, int32_t* elem0,
    int32_t* kind_id, uint8_t* host_flag,
    uint8_t* str_bytes, int32_t* str_lens, int32_t* n_strings, int str_cap) {

    Ctx* ctx = static_cast<Ctx*>(handle);
    const int P = int(ctx->paths.size());
    const int E = max_slots;
    const int L = ctx->str_len_cap;
    Interner interner;
    int e_used = 1;

    for (int b = 0; b < n_docs; ++b) {
        std::deque<Value> arena;
        Parser parser{docs[b], docs[b] + doc_lens[b], &arena};
        Value* root = parser.parse();
        kind_id[b] = -1;
        if (!parser.ok || root == nullptr) {
            host_flag[b] = 1;
            continue;
        }
        const Value* kind_v = obj_get(root, "kind");
        if (kind_v != nullptr && kind_v->t == Value::Str) {
            auto it = ctx->kinds.find(kind_v->str);
            if (it != ctx->kinds.end()) kind_id[b] = it->second;
        }

        std::vector<Slot> slots;
        for (int p = 0; p < P; ++p) {
            slots.clear();
            enumerate_slots(root, ctx->paths[p], 0, 1, -1, slots, max_slots);
            if (int(slots.size()) > max_slots) {
                host_flag[b] = 1;
                slots.resize(size_t(max_slots));
            }
            if (int(slots.size()) > e_used) e_used = int(slots.size());

            for (int e = 0; e < int(slots.size()); ++e) {
                const size_t o = (size_t(b) * P + p) * E + size_t(e);
                const Slot& slot = slots[size_t(e)];
                mask[o] = slot.mask;
                slot_valid[o] = 1;
                elem0[o] = slot.elem0;
                const Value* v = slot.leaf;
                if (v == nullptr) continue;  // phantom: T_ABSENT default
                switch (v->t) {
                    case Value::Null:
                        type_tag[o] = T_NULL;
                        break;
                    case Value::Bool: {
                        type_tag[o] = T_BOOL;
                        bool_val[o] = v->b ? 1 : 0;
                        str_id[o] = interner.intern(v->b ? "true" : "false");
                        break;
                    }
                    case Value::Num: {
                        type_tag[o] = T_NUM;
                        std::string text;
                        if (num_token_is_int(v->raw)) {
                            text = std::string(v->raw);
                            if (!text.empty() && text[0] == '+') text.erase(0, 1);
                        } else {
                            text = format_float_sci(strtod(
                                std::string(v->raw).c_str(), nullptr));
                        }
                        if (int(text.size()) <= L) str_id[o] = interner.intern(text);
                        int64_t micro;
                        if (quantity_to_micro(v->raw, &micro)) {
                            num_val[o] = micro;
                            num_ok[o] = 1;
                        } else {
                            host_flag[b] = 1;
                        }
                        break;
                    }
                    case Value::Str: {
                        type_tag[o] = T_STR;
                        if (int(v->str.size()) <= L) str_id[o] = interner.intern(v->str);
                        else host_flag[b] = 1;
                        int64_t micro;
                        if (quantity_to_micro(v->str, &micro)) {
                            num_val[o] = micro;
                            num_ok[o] = 1;
                        }
                        break;
                    }
                    case Value::Obj:
                        type_tag[o] = T_OBJ;
                        break;
                    case Value::Arr:
                        type_tag[o] = T_LIST;
                        break;
                }
            }
        }
    }

    const int V = int(interner.strings.size());
    if (V > str_cap) return -1;
    const int L = ctx->str_len_cap;
    for (int v = 0; v < V; ++v) {
        const std::string& s = interner.strings[size_t(v)];
        int len = int(s.size()) < L ? int(s.size()) : L;
        memcpy(str_bytes + size_t(v) * L, s.data(), size_t(len));
        str_lens[v] = len;
    }
    *n_strings = V < 1 ? 1 : V;
    return e_used;
}

}  // extern "C"
