// ktpu_flatten: resource JSON -> leaf slot tensors, the native twin of
// kyverno_tpu/models/flatten.py (same layout, byte-for-byte — a parity test
// in tests/ops/test_native_flatten.py diffs every array over the
// cross-check corpus).
//
// The reference engine has no native code (SURVEY.md header); this library
// is the new host-side component the north star calls for: admission
// payloads arrive as JSON bytes, and turning them into device tensors is
// the end-to-end bottleneck of the TPU path (bench.py flatten_s). It
// parses a JSON array of documents (one json.dumps for the whole batch on
// the Python side), enumerates the compiled path dictionary against each
// document, interns the string dictionary, and decomposes
// numbers/quantities/durations into exact i64 micro-units — mirroring
// models/flatten.py semantics including phantom slots, null-break chains,
// prefix-presence masks, request-envelope and effective-namespace roots,
// host-lane flags, and Go-style float stringification (utils/gofmt.py).
//
// C ABI only (consumed via ctypes; pybind11 is not in the image). The
// one Python-aware entry (ktpu_flatten_packed_py, walking live dicts to
// skip json.dumps) is guarded by KTPU_NO_PYTHON for builds without
// Python headers and is loaded via ctypes.PyDLL (GIL held).

#ifndef KTPU_NO_PYTHON
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#endif

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>
#include <charconv>
#include <deque>
#include <memory>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr char SEP = '\x1f';
constexpr int64_t NUM_SCALE_POW10 = 6;          // micro-units
constexpr int64_t NUM_MAX = int64_t(1) << 62;

// type tags (models/flatten.py)
enum : int8_t { T_ABSENT = 0, T_NULL, T_BOOL, T_NUM, T_STR, T_OBJ, T_LIST };

// ------------------------------------------------------------------ JSON

struct Value {
    enum Type : uint8_t { Null, Bool, Num, Str, Obj, Arr } t = Null;
    bool b = false;
    std::string_view raw;                       // Num: literal token text
    std::string str;                            // Str: decoded text
    std::vector<std::pair<std::string, Value*>> obj;
    std::vector<Value*> arr;
};

// Value pool: reset() reuses nodes (and their vector/string capacity)
// across documents, so steady-state parsing does no heap allocation.
struct Arena {
    std::deque<Value> store;
    size_t used = 0;

    Value* alloc() {
        if (used < store.size()) {
            Value* v = &store[used++];
            v->t = Value::Null;
            v->b = false;
            v->raw = {};
            v->str.clear();
            v->obj.clear();
            v->arr.clear();
            return v;
        }
        store.emplace_back();
        ++used;
        return &store.back();
    }

    void reset() { used = 0; }
};

struct Parser {
    const char* p;
    const char* end;
    Arena* arena;
    bool ok = true;

    Value* alloc() { return arena->alloc(); }

    void skip_ws() {
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
    }

    bool lit(const char* s, size_t n) {
        if (size_t(end - p) < n || memcmp(p, s, n) != 0) return false;
        p += n;
        return true;
    }

    Value* parse() {
        skip_ws();
        if (p >= end) { ok = false; return nullptr; }
        switch (*p) {
            case '{': return parse_obj();
            case '[': return parse_arr();
            case '"': return parse_str();
            case 't': { Value* v = alloc(); v->t = Value::Bool; v->b = true;
                        if (!lit("true", 4)) ok = false; return v; }
            case 'f': { Value* v = alloc(); v->t = Value::Bool; v->b = false;
                        if (!lit("false", 5)) ok = false; return v; }
            case 'n': { Value* v = alloc(); v->t = Value::Null;
                        if (!lit("null", 4)) ok = false; return v; }
            default:  return parse_num();
        }
    }

    Value* parse_obj() {
        Value* v = alloc(); v->t = Value::Obj;
        ++p;  // '{'
        skip_ws();
        if (p < end && *p == '}') { ++p; return v; }
        while (ok) {
            skip_ws();
            if (p >= end || *p != '"') { ok = false; break; }
            Value* key = parse_str();
            if (!ok) break;
            skip_ws();
            if (p >= end || *p != ':') { ok = false; break; }
            ++p;
            Value* val = parse();
            if (!ok) break;
            v->obj.emplace_back(std::move(key->str), val);
            skip_ws();
            if (p < end && *p == ',') { ++p; continue; }
            if (p < end && *p == '}') { ++p; break; }
            ok = false;
        }
        return v;
    }

    Value* parse_arr() {
        Value* v = alloc(); v->t = Value::Arr;
        ++p;  // '['
        skip_ws();
        if (p < end && *p == ']') { ++p; return v; }
        while (ok) {
            Value* el = parse();
            if (!ok) break;
            v->arr.push_back(el);
            skip_ws();
            if (p < end && *p == ',') { ++p; continue; }
            if (p < end && *p == ']') { ++p; break; }
            ok = false;
        }
        return v;
    }

    Value* parse_str() {
        Value* v = alloc(); v->t = Value::Str;
        ++p;  // opening '"'
        std::string& out = v->str;
        while (p < end && *p != '"') {
            if (*p == '\\') {
                ++p;
                if (p >= end) { ok = false; return v; }
                switch (*p) {
                    case '"': out += '"'; break;
                    case '\\': out += '\\'; break;
                    case '/': out += '/'; break;
                    case 'b': out += '\b'; break;
                    case 'f': out += '\f'; break;
                    case 'n': out += '\n'; break;
                    case 'r': out += '\r'; break;
                    case 't': out += '\t'; break;
                    case 'u': {
                        if (end - p < 5) { ok = false; return v; }
                        unsigned cp = 0;
                        for (int i = 1; i <= 4; ++i) {
                            char c = p[i];
                            cp <<= 4;
                            if (c >= '0' && c <= '9') cp |= unsigned(c - '0');
                            else if (c >= 'a' && c <= 'f') cp |= unsigned(c - 'a' + 10);
                            else if (c >= 'A' && c <= 'F') cp |= unsigned(c - 'A' + 10);
                            else { ok = false; return v; }
                        }
                        p += 4;
                        // surrogate pairs
                        if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 7 &&
                            p[1] == '\\' && p[2] == 'u') {
                            unsigned lo = 0;
                            bool lo_ok = true;
                            for (int i = 3; i <= 6; ++i) {
                                char c = p[i];
                                lo <<= 4;
                                if (c >= '0' && c <= '9') lo |= unsigned(c - '0');
                                else if (c >= 'a' && c <= 'f') lo |= unsigned(c - 'a' + 10);
                                else if (c >= 'A' && c <= 'F') lo |= unsigned(c - 'A' + 10);
                                else { lo_ok = false; break; }
                            }
                            if (lo_ok && lo >= 0xDC00 && lo <= 0xDFFF) {
                                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                                p += 6;
                            }
                        }
                        // utf-8 encode
                        if (cp < 0x80) out += char(cp);
                        else if (cp < 0x800) {
                            out += char(0xC0 | (cp >> 6));
                            out += char(0x80 | (cp & 0x3F));
                        } else if (cp < 0x10000) {
                            out += char(0xE0 | (cp >> 12));
                            out += char(0x80 | ((cp >> 6) & 0x3F));
                            out += char(0x80 | (cp & 0x3F));
                        } else {
                            out += char(0xF0 | (cp >> 18));
                            out += char(0x80 | ((cp >> 12) & 0x3F));
                            out += char(0x80 | ((cp >> 6) & 0x3F));
                            out += char(0x80 | (cp & 0x3F));
                        }
                        break;
                    }
                    default: ok = false; return v;
                }
                ++p;
            } else {
                out += *p++;
            }
        }
        if (p >= end) { ok = false; return v; }
        ++p;  // closing '"'
        return v;
    }

    Value* parse_num() {
        Value* v = alloc(); v->t = Value::Num;
        const char* start = p;
        if (p < end && (*p == '-' || *p == '+')) ++p;
        while (p < end && ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' ||
                           *p == 'E' || *p == '+' || *p == '-')) ++p;
        if (p == start) { ok = false; return v; }
        v->raw = std::string_view(start, size_t(p - start));
        return v;
    }
};

const Value* obj_get(const Value* v, std::string_view key) {
    if (v == nullptr || v->t != Value::Obj) return nullptr;
    for (const auto& kv : v->obj)
        if (kv.first == key) return kv.second;
    return nullptr;
}

// ------------------------------------------------------------ quantities

// Exact micro-unit decomposition of a quantity token (utils/quantity.py
// parse_quantity + models/flatten._value_to_micro). Returns false when not
// a quantity or not exactly representable in micro-units <= NUM_MAX.
bool quantity_to_micro(std::string_view s, int64_t* out,
                       bool* capped = nullptr) {
    // str.strip() (ASCII whitespace set is what occurs in JSON strings)
    auto is_ws = [](char c) {
        return c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
               c == '\f' || c == '\v';
    };
    while (!s.empty() && is_ws(s.front())) s.remove_prefix(1);
    while (!s.empty() && is_ws(s.back())) s.remove_suffix(1);
    if (s.empty()) return false;

    size_t i = 0;
    bool neg = false;
    if (s[i] == '+' || s[i] == '-') { neg = s[i] == '-'; ++i; }

    __int128 digits = 0;
    int n_int = 0, n_frac = 0;
    bool seen_dot = false;
    int total_digits = 0;
    for (; i < s.size(); ++i) {
        char c = s[i];
        if (c >= '0' && c <= '9') {
            if (++total_digits > 36) {
                // beyond the exact __int128 range; the Python tier has no
                // digit cap, so such leaves route to the host lane
                if (capped) *capped = true;
                return false;
            }
            digits = digits * 10 + (c - '0');
            if (seen_dot) ++n_frac; else ++n_int;
        } else if (c == '.' && !seen_dot) {
            seen_dot = true;
        } else {
            break;
        }
    }
    // _QUANTITY_RE: \d+(\.\d*)? | \.\d+  — a bare "." or ".suffix" is invalid
    if (n_int == 0 && n_frac == 0) return false;

    std::string_view suffix = s.substr(i);
    int pow10 = 0;
    int pow2 = 0;
    if (!suffix.empty()) {
        if (suffix == "Ki") pow2 = 10;
        else if (suffix == "Mi") pow2 = 20;
        else if (suffix == "Gi") pow2 = 30;
        else if (suffix == "Ti") pow2 = 40;
        else if (suffix == "Pi") pow2 = 50;
        else if (suffix == "Ei") pow2 = 60;
        else if (suffix == "n") pow10 = -9;
        else if (suffix == "u") pow10 = -6;
        else if (suffix == "m") pow10 = -3;
        else if (suffix == "k") pow10 = 3;
        else if (suffix == "M") pow10 = 6;
        else if (suffix == "G") pow10 = 9;
        else if (suffix == "T") pow10 = 12;
        else if (suffix == "P") pow10 = 15;
        else if (suffix == "E") pow10 = 18;
        else if (suffix[0] == 'e' || suffix[0] == 'E') {
            int exp = 0;
            bool eneg = false;
            size_t j = 1;
            if (j < suffix.size() && (suffix[j] == '+' || suffix[j] == '-')) {
                eneg = suffix[j] == '-';
                ++j;
            }
            if (j >= suffix.size()) return false;
            for (; j < suffix.size(); ++j) {
                if (suffix[j] < '0' || suffix[j] > '9') return false;
                exp = exp * 10 + (suffix[j] - '0');
                if (exp > 40) return false;
            }
            pow10 = eneg ? -exp : exp;
        } else {
            return false;
        }
    }

    // value = digits * 10^(-n_frac) * 2^pow2 * 10^pow10; micro = value*10^6
    __int128 num = digits;
    for (int k = 0; k < pow2; ++k) {
        num <<= 1;
        if (num > (__int128(1) << 100)) return false;
    }
    int scale = -n_frac + pow10 + int(NUM_SCALE_POW10);
    while (scale > 0) {
        num *= 10;
        --scale;
        if (num > (__int128(1) << 110)) return false;
    }
    while (scale < 0) {
        if (num % 10 != 0) return false;  // sub-micro precision
        num /= 10;
        ++scale;
    }
    if (num > __int128(NUM_MAX)) return false;
    *out = neg ? -int64_t(num) : int64_t(num);
    return true;
}

// std::from_chars for double is absent in libstdc++ < 11; strtod on the
// NUL-terminated copy parses the same token (callers pre-validate the
// digit shape, and LC_NUMERIC stays "C" inside extension modules).
inline double parse_double_tok(const std::string& tok) {
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
    double v = 0.0;
    std::from_chars(tok.data(), tok.data() + tok.size(), v);
    return v;
#else
    return strtod(tok.c_str(), nullptr);
#endif
}

// Go strconv.FormatFloat(v,'E',-1,64) — shortest mantissa, E+NN exponent
// (utils/gofmt.py format_float_sci).
std::string format_float_sci(double v) {
    if (v != v) return "NaN";
    if (v == __builtin_inf()) return "+Inf";
    if (v == -__builtin_inf()) return "-Inf";
    char buf[64];
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
    auto res = std::to_chars(buf, buf + sizeof buf, v);  // shortest repr
    std::string shortest(buf, res.ptr);
#else
    // libstdc++ < 11 has no floating-point to_chars: find the shortest
    // %g precision that round-trips — same digits as to_chars (minimal
    // length, correctly rounded), so byte parity with gofmt.py holds
    for (int prec = 1; prec <= 17; ++prec) {
        snprintf(buf, sizeof buf, "%.*g", prec, v);
        if (strtod(buf, nullptr) == v) break;
    }
    std::string shortest(buf);
#endif

    bool neg = false;
    std::string digits = shortest;
    if (!digits.empty() && digits[0] == '-') { neg = true; digits.erase(0, 1); }

    std::string mant_digits;
    int iexp = 0;
    auto epos = digits.find_first_of("eE");
    if (epos != std::string::npos) {
        std::string m = digits.substr(0, epos);
        iexp = atoi(digits.c_str() + epos + 1);
        auto dot = m.find('.');
        if (dot != std::string::npos) m.erase(dot, 1);
        while (m.size() > 1 && m.back() == '0') m.pop_back();
        mant_digits = m;
    } else {
        auto dot = digits.find('.');
        std::string int_part = dot == std::string::npos ? digits : digits.substr(0, dot);
        std::string frac = dot == std::string::npos ? "" : digits.substr(dot + 1);
        if (frac == "0") frac = "";
        while (!frac.empty() && frac.back() == '0') frac.pop_back();
        if (int_part == "0") {
            size_t nz = frac.find_first_not_of('0');
            if (nz == std::string::npos) return neg ? "-0E+00" : "0E+00";
            iexp = -int(nz) - 1;
            mant_digits = frac.substr(nz);
        } else {
            iexp = int(int_part.size()) - 1;
            mant_digits = int_part + frac;
            while (mant_digits.size() > 1 && mant_digits.back() == '0')
                mant_digits.pop_back();
        }
    }
    std::string out;
    if (neg) out += '-';
    out += mant_digits[0];
    if (mant_digits.size() > 1) {
        out += '.';
        out += mant_digits.substr(1);
    }
    out += 'E';
    out += iexp >= 0 ? '+' : '-';
    int a = iexp >= 0 ? iexp : -iexp;
    char eb[8];
    snprintf(eb, sizeof eb, "%02d", a);
    out += eb;
    return out;
}

// value_to_string_for_equality for a Num token: ints keep their text,
// floats format the Go way.
bool num_token_is_int(std::string_view raw) {
    for (char c : raw)
        if (c == '.' || c == 'e' || c == 'E') return false;
    return true;
}

// ------------------------------------------------------------ durations

// utils/duration.py parse_duration twin: Go time.ParseDuration dialect.
// Returns seconds; summation order and unit constants match the Python so
// the doubles (and the banker's rounding to micro below) agree bit-exactly.
bool parse_duration_secs(std::string_view s, double* out) {
    auto is_ws = [](char c) {
        return c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
               c == '\f' || c == '\v';
    };
    while (!s.empty() && is_ws(s.front())) s.remove_prefix(1);
    while (!s.empty() && is_ws(s.back())) s.remove_suffix(1);
    bool neg = false;
    if (!s.empty() && (s.front() == '+' || s.front() == '-')) {
        neg = s.front() == '-';
        s.remove_prefix(1);
    }
    if (s == "0") { *out = 0.0; return true; }
    if (s.empty()) return false;
    double total = 0.0;
    size_t i = 0;
    while (i < s.size()) {
        // number: \d+(\.\d*)? | \.\d+
        size_t start = i;
        int nd = 0, nf = 0;
        bool dot = false;
        while (i < s.size()) {
            char c = s[i];
            if (c >= '0' && c <= '9') { ++i; if (dot) ++nf; else ++nd; }
            else if (c == '.' && !dot) { dot = true; ++i; }
            else break;
        }
        if (nd == 0 && nf == 0) return false;
        double v = parse_double_tok(std::string(s.substr(start, i - start)));
        // unit (longest match first): ns us µs μs ms s m h
        double unit;
        if (s.compare(i, 2, "ns") == 0) { unit = 1e-9; i += 2; }
        else if (s.compare(i, 2, "us") == 0) { unit = 1e-6; i += 2; }
        else if (s.compare(i, 3, "\xc2\xb5s") == 0) { unit = 1e-6; i += 3; }
        else if (s.compare(i, 3, "\xce\xbcs") == 0) { unit = 1e-6; i += 3; }
        else if (s.compare(i, 2, "ms") == 0) { unit = 1e-3; i += 2; }
        else if (s.compare(i, 1, "s") == 0) { unit = 1.0; i += 1; }
        else if (s.compare(i, 1, "m") == 0) { unit = 60.0; i += 1; }
        else if (s.compare(i, 1, "h") == 0) { unit = 3600.0; i += 1; }
        else return false;
        total += v * unit;
    }
    *out = neg ? -total : total;
    return true;
}

// models/flatten._duration_micro: round(secs * 1e6) — Python round() is
// round-half-to-even, which nearbyint reproduces in the default FP mode.
bool duration_micro(std::string_view s, int64_t* out) {
    double secs;
    if (!parse_duration_secs(s, &secs)) return false;
    double m = std::nearbyint(secs * 1e6);
    if (std::fabs(m) > double(NUM_MAX)) return false;
    *out = int64_t(m);
    return true;
}

// Python float() acceptance (num_plain flag for string leaves). Mirrors
// CPython's float_from_string: optional ws, sign, inf/infinity/nan, or
// decimal with single underscores *between* digits.
bool py_float_ok(std::string_view s) {
    auto is_ws = [](char c) {
        return c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
               c == '\f' || c == '\v';
    };
    while (!s.empty() && is_ws(s.front())) s.remove_prefix(1);
    while (!s.empty() && is_ws(s.back())) s.remove_suffix(1);
    if (s.empty()) return false;
    size_t i = 0;
    if (s[i] == '+' || s[i] == '-') ++i;
    auto ci_is = [&](const char* word) {
        size_t n = strlen(word);
        if (s.size() - i != n) return false;
        for (size_t k = 0; k < n; ++k)
            if (tolower(s[i + k]) != word[k]) return false;
        return true;
    };
    if (ci_is("inf") || ci_is("infinity") || ci_is("nan")) return true;
    // digit run with single underscores between digits
    auto digits = [&](bool* any) {
        *any = false;
        bool prev_digit = false;
        while (i < s.size()) {
            char c = s[i];
            if (c >= '0' && c <= '9') { prev_digit = true; *any = true; ++i; }
            else if (c == '_') {
                if (!prev_digit || i + 1 >= s.size() ||
                    s[i + 1] < '0' || s[i + 1] > '9') return false;
                prev_digit = false;
                ++i;
            } else break;
        }
        return true;
    };
    bool int_any = false, frac_any = false;
    if (!digits(&int_any)) return false;
    if (i < s.size() && s[i] == '.') {
        ++i;
        if (!digits(&frac_any)) return false;
    }
    if (!int_any && !frac_any) return false;
    if (i < s.size() && (s[i] == 'e' || s[i] == 'E')) {
        ++i;
        if (i < s.size() && (s[i] == '+' || s[i] == '-')) ++i;
        bool exp_any = false;
        if (!digits(&exp_any) || !exp_any) return false;
    }
    return i == s.size();
}

// The Python tier parses strings with unicode-aware rules (str.strip()
// whitespace, regex \d, float()) while this library parses ASCII. The two
// can only disagree when the string contains a unicode whitespace or a
// non-ASCII decimal digit (ASCII success implies the string is pure ASCII)
// — or the \x1c-\x1f controls Python's str.isspace() accepts. Such leaves
// route the resource to the host lane, where the Python flattener is
// authoritative.
struct CpRange { uint32_t lo, hi; };
constexpr CpRange UNI_WS_OR_DIGIT[] = {
    {0x85,0x85},{0xA0,0xA0},{0x660,0x669},{0x6F0,0x6F9},{0x7C0,0x7C9},
    {0x966,0x96F},{0x9E6,0x9EF},{0xA66,0xA6F},{0xAE6,0xAEF},{0xB66,0xB6F},
    {0xBE6,0xBEF},{0xC66,0xC6F},{0xCE6,0xCEF},{0xD66,0xD6F},{0xDE6,0xDEF},
    {0xE50,0xE59},{0xED0,0xED9},{0xF20,0xF29},{0x1040,0x1049},
    {0x1090,0x1099},{0x1680,0x1680},{0x17E0,0x17E9},{0x1810,0x1819},
    {0x1946,0x194F},{0x19D0,0x19D9},{0x1A80,0x1A89},{0x1A90,0x1A99},
    {0x1B50,0x1B59},{0x1BB0,0x1BB9},{0x1C40,0x1C49},{0x1C50,0x1C59},
    {0x2000,0x200A},{0x2028,0x2029},{0x202F,0x202F},{0x205F,0x205F},
    {0x3000,0x3000},{0xA620,0xA629},{0xA8D0,0xA8D9},{0xA900,0xA909},
    {0xA9D0,0xA9D9},{0xA9F0,0xA9F9},{0xAA50,0xAA59},{0xABF0,0xABF9},
    {0xFF10,0xFF19},{0x104A0,0x104A9},{0x10D30,0x10D39},{0x11066,0x1106F},
    {0x110F0,0x110F9},{0x11136,0x1113F},{0x111D0,0x111D9},
    {0x112F0,0x112F9},{0x11450,0x11459},{0x114D0,0x114D9},
    {0x11650,0x11659},{0x116C0,0x116C9},{0x11730,0x11739},
    {0x118E0,0x118E9},{0x11950,0x11959},{0x11C50,0x11C59},
    {0x11D50,0x11D59},{0x11DA0,0x11DA9},{0x11F50,0x11F59},
    {0x16A60,0x16A69},{0x16AC0,0x16AC9},{0x16B50,0x16B59},
    {0x1D7CE,0x1D7FF},{0x1E140,0x1E149},{0x1E2F0,0x1E2F9},
    {0x1E4F0,0x1E4F9},{0x1E950,0x1E959},{0x1FBF0,0x1FBF9},
};

bool needs_python_parse(const std::string& s) {
    for (size_t i = 0; i < s.size();) {
        unsigned char c = s[i];
        if (c < 0x80) {
            if (c >= 0x1c && c <= 0x1f) return true;
            ++i;
            continue;
        }
        // decode one UTF-8 codepoint (already validated by the JSON layer)
        uint32_t cp;
        size_t n;
        if ((c & 0xE0) == 0xC0) { cp = c & 0x1F; n = 2; }
        else if ((c & 0xF0) == 0xE0) { cp = c & 0x0F; n = 3; }
        else if ((c & 0xF8) == 0xF0) { cp = c & 0x07; n = 4; }
        else { ++i; continue; }
        if (i + n > s.size()) return true;  // malformed: be conservative
        for (size_t k = 1; k < n; ++k) cp = (cp << 6) | (s[i + k] & 0x3F);
        i += n;
        for (const auto& r : UNI_WS_OR_DIGIT)
            if (cp >= r.lo && cp <= r.hi) return true;
    }
    return false;
}

// Python int(s, 10) acceptance (num_int lane for string leaves):
// whitespace strip, optional sign, digit runs with single underscores
// strictly between digits.
bool py_int_ok(std::string_view s) {
    auto is_ws = [](char c) {
        return c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
               c == '\f' || c == '\v';
    };
    while (!s.empty() && is_ws(s.front())) s.remove_prefix(1);
    while (!s.empty() && is_ws(s.back())) s.remove_suffix(1);
    if (s.empty()) return false;
    size_t i = 0;
    if (s[i] == '+' || s[i] == '-') ++i;
    bool any = false;
    bool prev_digit = false;
    while (i < s.size()) {
        char c = s[i];
        if (c >= '0' && c <= '9') { any = true; prev_digit = true; ++i; }
        else if (c == '_') {
            if (!prev_digit || i + 1 >= s.size() ||
                s[i + 1] < '0' || s[i + 1] > '9') return false;
            prev_digit = false;
            ++i;
        } else return false;
    }
    return any;
}

// ------------------------------------------------------------------ ctx

struct Ctx {
    std::vector<std::vector<std::string>> paths;   // split segments
    std::unordered_map<std::string, int32_t> kinds;
    std::string req_mark, nseff_mark;
    int str_len_cap = 64;
};

struct Interner {
    std::unordered_map<std::string, int32_t> index;
    std::vector<std::string> strings;

    int32_t intern(const std::string& s) {
        auto it = index.find(s);
        if (it != index.end()) return it->second;
        int32_t id = int32_t(strings.size());
        index.emplace(s, id);
        strings.push_back(s);
        return id;
    }
};

struct Slot {
    uint16_t mask;
    int32_t elem0;
    const Value* leaf;      // non-null only when leaf_present
    bool leaf_present;      // distinguishes JSON null leaf from phantom
    bool null_break;        // chain broke at an existing non-map node
};

// _enumerate_slots walk(): identical traversal and bit layout.
void walk_slots(const Value* node, const std::vector<std::string>& segs,
                size_t i, size_t offset, uint16_t mask, int32_t elem0,
                std::vector<Slot>& out, int cap) {
    if (int(out.size()) > cap) return;
    if (i == segs.size()) {
        out.push_back({mask, elem0, node, true, false});
        return;
    }
    const std::string& seg = segs[i];
    uint16_t bit = uint16_t(1u << (i + 1 + offset));
    if (seg == "*") {
        if (node == nullptr || node->t != Value::Arr) {
            // list pattern over an existing non-list node: structural break
            out.push_back({mask, elem0, nullptr, false, true});
            return;
        }
        int32_t idx = 0;
        for (const Value* el : node->arr) {
            walk_slots(el, segs, i + 1, offset, uint16_t(mask | bit),
                       elem0 < 0 ? idx : elem0, out, cap);
            ++idx;
        }
    } else {
        if (node == nullptr || node->t != Value::Obj) {
            out.push_back({mask, elem0, nullptr, false, true});
            return;
        }
        const Value* child = obj_get(node, seg);
        if (child == nullptr) {
            out.push_back({mask, elem0, nullptr, false, false});
            return;
        }
        walk_slots(child, segs, i + 1, offset, uint16_t(mask | bit), elem0, out, cap);
    }
}

}  // namespace

extern "C" {

// paths: '\n'-joined SEP-separated generalized paths
// kinds: '\n'-joined kind names (index == id, matching tensors.kind_index)
// req_mark / nseff_mark: the ir.REQ_MARK / ir.NSEFF_MARK sentinel segments
void* ktpu_create(const char* paths, const char* kinds, int str_len_cap,
                  const char* req_mark, const char* nseff_mark) {
    auto* ctx = new Ctx;
    ctx->str_len_cap = str_len_cap;
    ctx->req_mark = req_mark ? req_mark : "";
    ctx->nseff_mark = nseff_mark ? nseff_mark : "";
    std::string_view pv(paths ? paths : "");
    size_t start = 0;
    while (start <= pv.size() && !pv.empty()) {
        size_t nl = pv.find('\n', start);
        std::string_view line = pv.substr(
            start, nl == std::string_view::npos ? pv.size() - start : nl - start);
        if (!line.empty()) {
            std::vector<std::string> segs;
            size_t s0 = 0;
            while (true) {
                size_t sp = line.find(SEP, s0);
                if (sp == std::string_view::npos) {
                    segs.emplace_back(line.substr(s0));
                    break;
                }
                segs.emplace_back(line.substr(s0, sp - s0));
                s0 = sp + 1;
            }
            ctx->paths.push_back(std::move(segs));
        }
        if (nl == std::string_view::npos) break;
        start = nl + 1;
    }
    std::string_view kv(kinds ? kinds : "");
    start = 0;
    int32_t kid = 0;
    while (start <= kv.size() && !kv.empty()) {
        size_t nl = kv.find('\n', start);
        std::string_view line = kv.substr(
            start, nl == std::string_view::npos ? kv.size() - start : nl - start);
        if (!line.empty()) ctx->kinds.emplace(std::string(line), kid++);
        if (nl == std::string_view::npos) break;
        start = nl + 1;
    }
    return ctx;
}

void ktpu_destroy(void* handle) { delete static_cast<Ctx*>(handle); }

// Streams the next element out of a top-level JSON array: consumes '[' on
// the first call, then one value and its ',' / ']' delimiter per call.
// Keeps memory flat: one document's tree lives in the arena at a time.
struct ArrayStream {
    Parser parser;
    bool started = false;
    bool done = false;

    Value* next() {
        parser.skip_ws();
        if (!started) {
            if (parser.p >= parser.end || *parser.p != '[') {
                parser.ok = false;
                return nullptr;
            }
            ++parser.p;
            started = true;
            parser.skip_ws();
            if (parser.p < parser.end && *parser.p == ']') {
                ++parser.p;
                done = true;
                return nullptr;
            }
        }
        if (done) return nullptr;
        Value* v = parser.parse();
        if (!parser.ok) return nullptr;
        parser.skip_ws();
        if (parser.p < parser.end && *parser.p == ',') ++parser.p;
        else if (parser.p < parser.end && *parser.p == ']') done = true;
        else parser.ok = false;
        return parser.ok ? v : nullptr;
    }
};

// Flatten a batch. ``docs`` is a JSON *array* of resource documents
// (one json.dumps of the whole batch); ``reqs`` optionally a same-length
// JSON array of admission envelopes (or NULL). [B,P,e_cap] arrays are laid
// out row-major; slot lists are truncated to max_slots (host_flag beyond
// that, as in flatten.py). Returns e_used (>=1, <= e_cap), or
//  -1  string dictionary exceeded str_cap (*n_strings = size needed)
//  -2  top-level parse failure
//  -3  array length != n_docs
//  -4  a slot list exceeded e_cap (*e_needed = stride to retry with)
int ktpu_flatten_batch(
    void* handle,
    const char* docs, int64_t docs_len,
    const char* reqs, int64_t reqs_len,
    int n_docs, int max_slots, int e_cap, int32_t* e_needed,
    uint16_t* mask, uint8_t* slot_valid, uint8_t* null_break,
    int8_t* type_tag, int32_t* str_id,
    int64_t* num_val, uint8_t* num_ok, uint8_t* num_plain, uint8_t* num_int,
    int64_t* dur_val, uint8_t* dur_ok, uint8_t* dur_any,
    uint8_t* bool_val, int32_t* elem0,
    int32_t* kind_id, uint8_t* host_flag,
    uint8_t* str_bytes, int32_t* str_lens, uint8_t* str_glob,
    int32_t* n_strings, int str_cap) {

    Ctx* ctx = static_cast<Ctx*>(handle);
    const int P = int(ctx->paths.size());
    const int E = e_cap;
    const int L = ctx->str_len_cap;

    Arena arena;
    ArrayStream doc_stream{Parser{docs, docs + docs_len, &arena}};
    ArrayStream req_stream{Parser{reqs, reqs + (reqs ? reqs_len : 0), &arena}};

    Interner interner;
    int e_used = 1;
    std::vector<Slot> slots;
    Value nseff_leaf;          // synthetic Str node for NSEFF slots
    nseff_leaf.t = Value::Str;

    for (int b = 0; b < n_docs; ++b) {
        arena.reset();         // previous document's tree: memory stays flat
        const Value* root = doc_stream.next();
        if (!doc_stream.parser.ok) return -2;
        if (root == nullptr) return -3;  // array shorter than n_docs
        const Value* env = nullptr;
        if (reqs != nullptr) {
            env = req_stream.next();
            if (!req_stream.parser.ok) return -2;
            if (env == nullptr) return -3;
        }
        const bool env_nonempty =
            env != nullptr && env->t == Value::Obj && !env->obj.empty();

        // kind id + effective namespace (flatten.py _effective_namespace)
        kind_id[b] = -1;
        std::string ns_eff;
        if (root != nullptr && root->t == Value::Obj) {
            const Value* kind_v = obj_get(root, "kind");
            std::string kind = kind_v && kind_v->t == Value::Str ? kind_v->str : "";
            auto it = ctx->kinds.find(kind);
            if (it != ctx->kinds.end()) kind_id[b] = it->second;
            const Value* meta = obj_get(root, "metadata");
            const Value* nv = obj_get(
                meta, kind == "Namespace" ? "name" : "namespace");
            if (nv != nullptr && nv->t == Value::Str) ns_eff = nv->str;
        }

        for (int p = 0; p < P; ++p) {
            slots.clear();
            const auto& segs = ctx->paths[p];
            if (!segs.empty() && segs[0] == ctx->nseff_mark) {
                nseff_leaf.str = ns_eff;
                slots.push_back({0b11, -1, &nseff_leaf, true, false});
            } else if (!segs.empty() && segs[0] == ctx->req_mark) {
                uint16_t base_mask = env_nonempty ? 0b11 : 0b1;
                if (segs.size() == 1 || !env_nonempty) {
                    slots.push_back({base_mask, -1, nullptr, false, false});
                } else {
                    // start at segment 1 with offset 0: bit = 1 << (i + 1)
                    // equals the Python rest-walk's 1 << (j + 1 + offset)
                    walk_slots(env, segs, 1, 0, base_mask, -1, slots, max_slots);
                }
            } else if (root == nullptr || root->t == Value::Null) {
                // flatten.py: `if root is None` -> single phantom slot
                slots.push_back({0b1, -1, nullptr, false, false});
            } else {
                walk_slots(root, segs, 0, 0, 0b1, -1, slots, max_slots);
            }

            if (int(slots.size()) > max_slots) {
                host_flag[b] = 1;
                slots.resize(size_t(max_slots));
            }
            if (int(slots.size()) > E) {
                *e_needed = int(slots.size());
                return -4;     // caller re-allocates with a larger stride
            }
            if (int(slots.size()) > e_used) e_used = int(slots.size());

            for (int e = 0; e < int(slots.size()); ++e) {
                const size_t o = (size_t(b) * P + p) * E + size_t(e);
                const Slot& slot = slots[size_t(e)];
                mask[o] = slot.mask;
                slot_valid[o] = 1;
                null_break[o] = slot.null_break ? 1 : 0;
                elem0[o] = slot.elem0;
                if (!slot.leaf_present) continue;  // phantom: T_ABSENT default
                const Value* v = slot.leaf;
                switch (v->t) {
                    case Value::Null:
                        type_tag[o] = T_NULL;
                        break;
                    case Value::Bool: {
                        type_tag[o] = T_BOOL;
                        bool_val[o] = v->b ? 1 : 0;
                        str_id[o] = interner.intern(v->b ? "true" : "false");
                        break;
                    }
                    case Value::Num: {
                        type_tag[o] = T_NUM;
                        const bool is_int = num_token_is_int(v->raw);
                        num_int[o] = is_int ? 1 : 0;
                        std::string text;
                        if (is_int) {
                            text = std::string(v->raw);
                            if (!text.empty() && text[0] == '+') text.erase(0, 1);
                        } else {
                            double fv = parse_double_tok(std::string(v->raw));
                            text = format_float_sci(fv);
                        }
                        if (int(text.size()) <= L) str_id[o] = interner.intern(text);
                        int64_t micro;
                        if (quantity_to_micro(v->raw, &micro)) {
                            num_val[o] = micro;
                            num_ok[o] = 1;
                            num_plain[o] = 1;
                        } else {
                            host_flag[b] = 1;
                        }
                        break;
                    }
                    case Value::Str: {
                        type_tag[o] = T_STR;
                        if (int(v->str.size()) <= L) str_id[o] = interner.intern(v->str);
                        else host_flag[b] = 1;
                        if (needs_python_parse(v->str)) {
                            // unicode-sensitive parse: empty numeric lanes,
                            // oracle evaluates this resource (host lane)
                            host_flag[b] = 1;
                            break;
                        }
                        int64_t micro;
                        bool capped = false;
                        const bool q_ok =
                            quantity_to_micro(v->str, &micro, &capped);
                        if (!q_ok && capped) {
                            // >36-digit number part: exact range exceeded
                            host_flag[b] = 1;
                            break;
                        }
                        num_int[o] = py_int_ok(v->str) ? 1 : 0;
                        if (q_ok) {
                            num_val[o] = micro;
                            num_ok[o] = 1;
                            if (py_float_ok(v->str)) num_plain[o] = 1;
                        }
                        int64_t dmicro;
                        if (duration_micro(v->str, &dmicro)) {
                            dur_val[o] = dmicro;
                            dur_any[o] = 1;
                            dur_ok[o] = v->str != "0" ? 1 : 0;
                        }
                        break;
                    }
                    case Value::Obj:
                        type_tag[o] = T_OBJ;
                        break;
                    case Value::Arr:
                        type_tag[o] = T_LIST;
                        break;
                }
            }
        }
    }

    if (!doc_stream.done) {
        // n_docs == 0 with "[]" still pending, or extra elements: check
        if (doc_stream.next() != nullptr || !doc_stream.done) return -3;
        if (!doc_stream.parser.ok) return -2;
    }

    const int V = int(interner.strings.size());
    *n_strings = V;  // on overflow: tells the caller the exact size to retry
    if (V > str_cap) return -1;
    for (int v = 0; v < V; ++v) {
        const std::string& s = interner.strings[size_t(v)];
        int len = int(s.size()) < L ? int(s.size()) : L;
        memcpy(str_bytes + size_t(v) * size_t(L), s.data(), size_t(len));
        str_lens[v] = len;
        str_glob[v] =
            s.find('*') != std::string::npos || s.find('?') != std::string::npos
                ? 1 : 0;
    }
    return e_used;
}

}  // extern "C"

// ------------------------------------------------- packed transfer format

namespace {

// Per-unique-string dictionary row (models/flatten.py pack_batch layout):
//   d0: num_lo(31) | num_ok<<31        d1: num_hi (two's complement)
//   d2: dur_lo(31) | dur_ok<<31        d3: dur_hi (two's complement)
//   d4: str_len(7) | has_glob<<7 | bool_val<<8 | dur_any<<9 | num_plain<<10
// plus flattener-internal bits (never emitted): host (string routes the
// resource to the CPU oracle) and pyint (int(s, 10)-parseable — the
// num_int *cell* bit for T_STR leaves).
struct DictRow {
    uint32_t d[5] = {0, 0, 0, 0, 0};
    bool host = false;
    bool pyint = false;
};

DictRow analyze_string(const std::string& s, int L) {
    DictRow r;
    uint32_t ln = uint32_t(int(s.size()) < L ? int(s.size()) : L);
    bool glob = s.find('*') != std::string::npos ||
                s.find('?') != std::string::npos;
    r.d[4] = ln | (uint32_t(glob) << 7) | (uint32_t(s == "true") << 8);
    // mirror the T_STR leaf branch order exactly: a host-parse or
    // digit-capped string leaves every value lane empty (incl. num_int)
    if (needs_python_parse(s)) { r.host = true; return r; }
    int64_t micro;
    bool capped = false;
    const bool q_ok = quantity_to_micro(s, &micro, &capped);
    if (!q_ok && capped) { r.host = true; return r; }
    r.pyint = py_int_ok(s);
    if (q_ok) {
        r.d[0] = uint32_t(micro & 0x7FFFFFFF) | (uint32_t(1) << 31);
        r.d[1] = uint32_t(uint64_t(micro >> 31) & 0xFFFFFFFFu);
        if (py_float_ok(s)) r.d[4] |= uint32_t(1) << 10;
    }
    int64_t dmicro;
    if (duration_micro(s, &dmicro)) {
        r.d[2] = uint32_t(dmicro & 0x7FFFFFFF) |
                 (uint32_t(s != "0") << 31);
        r.d[3] = uint32_t(uint64_t(dmicro >> 31) & 0xFFFFFFFFu);
        r.d[4] |= uint32_t(1) << 9;
    }
    return r;
}

// Interner that analyzes each unique string once — the per-leaf value
// parsing (quantity/duration/int/float) that dominated the unpacked
// flattener's leaf loop amortizes across every repeated occurrence.
struct PackedInterner {
    std::unordered_map<std::string, int32_t> index;
    std::vector<std::string> strings;
    std::vector<DictRow> rows;
    int L;

    explicit PackedInterner(int cap) : L(cap) {}

    int32_t intern(const std::string& s) {
        auto it = index.find(s);
        if (it != index.end()) return it->second;
        int32_t id = int32_t(strings.size());
        index.emplace(s, id);
        strings.push_back(s);
        rows.push_back(analyze_string(s, L));
        return id;
    }
};

constexpr uint32_t ELEM0_CAP = 254;  // mirrors flatten.ELEM0_CAP

// Per-document packed flatten: one instance per (sequential run | thread
// shard), writing cells/bmeta rows for the documents it is handed and
// interning into its own dictionary. Shared by the JSON-stream, threaded,
// and PyObject entry points so the cell semantics exist exactly once.
struct PackedCore {
    Ctx* ctx;
    int P, E, L, max_slots;
    uint32_t* cells;        // global [n_docs, P, E, 2] base pointer
    uint32_t* bmeta;        // global [n_docs]
    PackedInterner interner;
    int e_used = 1;
    std::vector<Slot> slots;
    Value nseff_leaf;

    PackedCore(Ctx* c, int e_cap, int max_slots_,
               uint32_t* cells_, uint32_t* bmeta_)
        : ctx(c), P(int(c->paths.size())), E(e_cap), L(c->str_len_cap),
          max_slots(max_slots_), cells(cells_), bmeta(bmeta_),
          interner(c->str_len_cap) {
        nseff_leaf.t = Value::Str;
    }

    // 0 ok; -4 slot list exceeded the stride (*e_needed = required)
    int doc(const Value* root, const Value* env, int b, int32_t* e_needed) {
        const bool env_nonempty =
            env != nullptr && env->t == Value::Obj && !env->obj.empty();

        int32_t kid = -1;
        bool host = false;
        std::string ns_eff;
        if (root != nullptr && root->t == Value::Obj) {
            const Value* kind_v = obj_get(root, "kind");
            std::string kind = kind_v && kind_v->t == Value::Str ? kind_v->str : "";
            auto it = ctx->kinds.find(kind);
            if (it != ctx->kinds.end()) kid = it->second;
            const Value* meta = obj_get(root, "metadata");
            const Value* nv = obj_get(
                meta, kind == "Namespace" ? "name" : "namespace");
            if (nv != nullptr && nv->t == Value::Str) ns_eff = nv->str;
        }

        for (int p = 0; p < P; ++p) {
            slots.clear();
            const auto& segs = ctx->paths[p];
            if (!segs.empty() && segs[0] == ctx->nseff_mark) {
                nseff_leaf.str = ns_eff;
                slots.push_back({0b11, -1, &nseff_leaf, true, false});
            } else if (!segs.empty() && segs[0] == ctx->req_mark) {
                uint16_t base_mask = env_nonempty ? 0b11 : 0b1;
                if (segs.size() == 1 || !env_nonempty) {
                    slots.push_back({base_mask, -1, nullptr, false, false});
                } else {
                    walk_slots(env, segs, 1, 0, base_mask, -1, slots, max_slots);
                }
            } else if (root == nullptr || root->t == Value::Null) {
                slots.push_back({0b1, -1, nullptr, false, false});
            } else {
                walk_slots(root, segs, 0, 0, 0b1, -1, slots, max_slots);
            }

            if (int(slots.size()) > max_slots) {
                host = true;
                slots.resize(size_t(max_slots));
            }
            if (int(slots.size()) > E) {
                *e_needed = int(slots.size());
                return -4;
            }
            if (int(slots.size()) > e_used) e_used = int(slots.size());

            uint32_t* row = cells + (size_t(b) * P + p) * size_t(E) * 2;
            for (int e = 0; e < int(slots.size()); ++e) {
                const Slot& slot = slots[size_t(e)];
                uint32_t e0w;
                if (slot.elem0 < 0) {
                    e0w = 0;
                } else if (uint32_t(slot.elem0) >= ELEM0_CAP) {
                    e0w = 255;
                    host = true;
                } else {
                    e0w = uint32_t(slot.elem0) + 1;
                }
                uint32_t tag = T_ABSENT;
                int32_t sid = -1;
                uint32_t numint = 0;
                if (slot.leaf_present) {
                    const Value* v = slot.leaf;
                    switch (v->t) {
                        case Value::Null:
                            tag = T_NULL;
                            break;
                        case Value::Bool:
                            tag = T_BOOL;
                            sid = interner.intern(v->b ? "true" : "false");
                            break;
                        case Value::Num: {
                            tag = T_NUM;
                            numint = num_token_is_int(v->raw) ? 1 : 0;
                            std::string text;
                            if (numint) {
                                text = std::string(v->raw);
                                if (!text.empty() && text[0] == '+')
                                    text.erase(0, 1);
                            } else {
                                double fv =
                                    parse_double_tok(std::string(v->raw));
                                text = format_float_sci(fv);
                            }
                            if (int(text.size()) <= L) {
                                sid = interner.intern(text);
                            } else {
                                // the packed value lanes live on the
                                // dictionary row; without one the number
                                // is unrepresentable -> CPU oracle
                                host = true;
                            }
                            int64_t micro;
                            if (!quantity_to_micro(v->raw, &micro))
                                host = true;
                            break;
                        }
                        case Value::Str: {
                            tag = T_STR;
                            if (int(v->str.size()) <= L) {
                                sid = interner.intern(v->str);
                                const DictRow& r = interner.rows[size_t(sid)];
                                host |= r.host;
                                numint = r.pyint ? 1 : 0;
                            } else {
                                host = true;
                            }
                            break;
                        }
                        case Value::Obj:
                            tag = T_OBJ;
                            break;
                        case Value::Arr:
                            tag = T_LIST;
                            break;
                    }
                }
                row[size_t(e) * 2] = uint32_t(sid + 1);
                row[size_t(e) * 2 + 1] =
                    uint32_t(slot.mask)
                    | (tag << 16)
                    | (uint32_t(1) << 19)                     // slot_valid
                    | (uint32_t(slot.null_break ? 1 : 0) << 20)
                    | (numint << 21)
                    | (e0w << 22);
            }
        }
        bmeta[b] = uint32_t(kid + 1)
                   | (uint32_t(host ? 1 : 0) << 16)
                   | (uint32_t(1) << 17);                     // live
        return 0;
    }
};

// Emit the interner's dictionary into the output arrays; -1 on overflow.
int emit_dict(const PackedInterner& interner, uint32_t* dictv,
              uint8_t* str_bytes, int32_t* n_strings, int str_cap, int L) {
    const int V = int(interner.strings.size());
    *n_strings = V;
    if (V > str_cap) return -1;
    for (int v = 0; v < V; ++v) {
        const std::string& s = interner.strings[size_t(v)];
        int len = int(s.size()) < L ? int(s.size()) : L;
        memcpy(str_bytes + size_t(v) * size_t(L), s.data(), size_t(len));
        memcpy(dictv + size_t(v) * 5, interner.rows[size_t(v)].d,
               5 * sizeof(uint32_t));
    }
    return 0;
}

// Byte ranges of the elements of a top-level JSON array (no validation of
// the element bodies — the per-shard Parser does that). False: malformed
// at the array level.
bool scan_array_elements(
    const char* p, const char* end,
    std::vector<std::pair<const char*, const char*>>& out) {
    auto ws = [](char c) {
        return c == ' ' || c == '\t' || c == '\n' || c == '\r';
    };
    while (p < end && ws(*p)) ++p;
    if (p >= end || *p != '[') return false;
    ++p;
    while (true) {
        while (p < end && ws(*p)) ++p;
        if (p >= end) return false;
        if (*p == ']') return true;
        const char* start = p;
        int depth = 0;
        bool in_str = false;
        while (p < end) {
            char c = *p;
            if (in_str) {
                if (c == '\\') { p += 2; continue; }
                if (c == '"') in_str = false;
                ++p;
            } else if (c == '"') { in_str = true; ++p; }
            else if (c == '{' || c == '[') { ++depth; ++p; }
            else if (c == '}' || c == ']') {
                if (depth == 0) break;       // the array's own ']'
                --depth; ++p;
            } else if (c == ',' && depth == 0) break;
            else ++p;
        }
        if (p > end) return false;
        out.emplace_back(start, p);
        while (p < end && ws(*p)) ++p;
        if (p >= end) return false;
        if (*p == ',') { ++p; continue; }
        if (*p == ']') return true;
        return false;
    }
}

int flatten_threads() {
    const char* env = getenv("KTPU_FLATTEN_THREADS");
    if (env != nullptr && *env != '\0') {
        int n = atoi(env);
        if (n >= 1) return n < 64 ? n : 64;
    }
    unsigned hw = std::thread::hardware_concurrency();
    int n = hw == 0 ? 1 : int(hw);
    return n < 8 ? n : 8;
}

// Threaded packed flatten over pre-scanned element ranges. Byte-parity
// with the sequential path: each shard interns locally in document order,
// and the shard-order first-wins merge reproduces the sequential
// first-appearance interning order exactly (all strings first seen in
// shard k precede — in the same relative order — those first seen in
// shard k+1, because shard k's documents do).
int packed_parallel(
    Ctx* ctx,
    const std::vector<std::pair<const char*, const char*>>& doc_spans,
    const std::vector<std::pair<const char*, const char*>>& req_spans,
    bool have_reqs, int n_docs, int max_slots, int e_cap, int32_t* e_needed,
    uint32_t* cells, uint32_t* bmeta, uint32_t* dictv, uint8_t* str_bytes,
    int32_t* n_strings, int str_cap, int T) {

    const int P = int(ctx->paths.size());
    const int L = ctx->str_len_cap;
    std::vector<std::unique_ptr<PackedCore>> cores;
    cores.resize(size_t(T));
    std::vector<int> shard_lo, shard_hi;
    shard_lo.resize(size_t(T));
    shard_hi.resize(size_t(T));
    std::atomic<int> err{0};
    std::atomic<int> need{0};
    const int per = (n_docs + T - 1) / T;

    auto shard_run = [&](int t) {
        const int lo = t * per;
        const int hi = lo + per < n_docs ? lo + per : n_docs;
        shard_lo[size_t(t)] = lo;
        shard_hi[size_t(t)] = hi;
        auto core = std::make_unique<PackedCore>(
            ctx, e_cap, max_slots, cells, bmeta);
        Arena arena;
        for (int b = lo; b < hi && err.load(std::memory_order_relaxed) == 0;
             ++b) {
            arena.reset();
            Parser dp{doc_spans[size_t(b)].first,
                      doc_spans[size_t(b)].second, &arena};
            const Value* root = dp.parse();
            if (!dp.ok) { err.store(-2); break; }
            const Value* env = nullptr;
            if (have_reqs) {
                Parser rp{req_spans[size_t(b)].first,
                          req_spans[size_t(b)].second, &arena};
                env = rp.parse();
                if (!rp.ok) { err.store(-2); break; }
            }
            int32_t en = 0;
            int rc = core->doc(root, env, b, &en);
            if (rc == -4) {
                int cur = need.load();
                while (en > cur && !need.compare_exchange_weak(cur, en)) {}
                err.store(-4);
                break;
            }
        }
        cores[size_t(t)] = std::move(core);
    };

    std::vector<std::thread> threads;
    threads.reserve(size_t(T - 1));
    for (int t = 1; t < T; ++t) threads.emplace_back(shard_run, t);
    shard_run(0);
    for (auto& th : threads) th.join();

    if (err.load() != 0) {
        if (err.load() == -4) *e_needed = need.load();
        return err.load();
    }

    // order-preserving first-wins merge of the shard dictionaries
    PackedInterner global(L);
    std::vector<std::vector<int32_t>> remap;
    remap.resize(size_t(T));
    int e_used = 1;
    for (int t = 0; t < T; ++t) {
        PackedInterner& loc = cores[size_t(t)]->interner;
        if (cores[size_t(t)]->e_used > e_used) e_used = cores[size_t(t)]->e_used;
        auto& rm = remap[size_t(t)];
        rm.resize(loc.strings.size());
        for (size_t i = 0; i < loc.strings.size(); ++i) {
            const std::string& s = loc.strings[i];
            auto it = global.index.find(s);
            int32_t gid;
            if (it == global.index.end()) {
                gid = int32_t(global.strings.size());
                global.index.emplace(s, gid);
                global.strings.push_back(s);
                // the row is a pure function of the string: carry it over
                global.rows.push_back(loc.rows[i]);
            } else {
                gid = it->second;
            }
            rm[i] = gid;
        }
    }

    // remap cell word0 (local sid + 1 -> global sid + 1), in parallel
    auto remap_run = [&](int t) {
        const auto& rm = remap[size_t(t)];
        const size_t row_words = size_t(P) * size_t(e_cap) * 2;
        for (int b = shard_lo[size_t(t)]; b < shard_hi[size_t(t)]; ++b) {
            uint32_t* row = cells + size_t(b) * row_words;
            for (size_t i = 0; i < row_words; i += 2) {
                uint32_t w0 = row[i];
                if (w0 != 0) row[i] = uint32_t(rm[size_t(w0 - 1)]) + 1;
            }
        }
    };
    threads.clear();
    for (int t = 1; t < T; ++t) threads.emplace_back(remap_run, t);
    remap_run(0);
    for (auto& th : threads) th.join();

    int rc = emit_dict(global, dictv, str_bytes, n_strings, str_cap, L);
    return rc < 0 ? rc : e_used;
}

}  // namespace

extern "C" {

// Flatten a batch straight into the packed transfer form
// (flatten.PACKED_BATCH_ARRAYS): cells uint32 [B,P,e_cap,2], bmeta uint32
// [B], dictv uint32 [str_cap,5], str_bytes uint8 [str_cap,L]. Same input
// conventions and -1/-2/-3/-4 retry protocol as ktpu_flatten_batch.
// Differences from the unpacked form are exactly the packed-lane caps:
// a resource hosts when elem0 exceeds ELEM0_CAP or a numeric/duration
// value lives on a string too long to intern (the cell lanes that carried
// such values are gone; the CPU oracle re-walks the document instead).
// Batches large enough to amortize a thread fan-out shard across
// std::thread workers (KTPU_FLATTEN_THREADS overrides the count; the
// result is byte-identical to the sequential path).
int ktpu_flatten_packed(
    void* handle,
    const char* docs, int64_t docs_len,
    const char* reqs, int64_t reqs_len,
    int n_docs, int max_slots, int e_cap, int32_t* e_needed,
    uint32_t* cells, uint32_t* bmeta, uint32_t* dictv,
    uint8_t* str_bytes,
    int32_t* n_strings, int str_cap) {

    Ctx* ctx = static_cast<Ctx*>(handle);
    const int L = ctx->str_len_cap;

    const int T = flatten_threads();
    if (T > 1 && n_docs >= 2 * T && n_docs >= 64) {
        std::vector<std::pair<const char*, const char*>> doc_spans;
        doc_spans.reserve(size_t(n_docs));
        if (scan_array_elements(docs, docs + docs_len, doc_spans) &&
            int(doc_spans.size()) == n_docs) {
            std::vector<std::pair<const char*, const char*>> req_spans;
            bool reqs_ok = true;
            if (reqs != nullptr) {
                req_spans.reserve(size_t(n_docs));
                reqs_ok = scan_array_elements(
                              reqs, reqs + reqs_len, req_spans) &&
                          int(req_spans.size()) == n_docs;
            }
            if (reqs_ok) {
                int threads = T;
                if (n_docs / threads < 32) threads = n_docs / 32;
                if (threads < 2) threads = 2;
                return packed_parallel(
                    ctx, doc_spans, req_spans, reqs != nullptr, n_docs,
                    max_slots, e_cap, e_needed, cells, bmeta, dictv,
                    str_bytes, n_strings, str_cap, threads);
            }
        }
        // array-level scan failed: fall through to the sequential parser,
        // which reports the precise -2/-3
    }

    Arena arena;
    ArrayStream doc_stream{Parser{docs, docs + docs_len, &arena}};
    ArrayStream req_stream{Parser{reqs, reqs + (reqs ? reqs_len : 0), &arena}};

    PackedCore core(ctx, e_cap, max_slots, cells, bmeta);
    for (int b = 0; b < n_docs; ++b) {
        arena.reset();
        const Value* root = doc_stream.next();
        if (!doc_stream.parser.ok) return -2;
        if (root == nullptr) return -3;
        const Value* env = nullptr;
        if (reqs != nullptr) {
            env = req_stream.next();
            if (!req_stream.parser.ok) return -2;
            if (env == nullptr) return -3;
        }
        int rc = core.doc(root, env, b, e_needed);
        if (rc != 0) return rc;
    }

    if (!doc_stream.done) {
        if (doc_stream.next() != nullptr || !doc_stream.done) return -3;
        if (!doc_stream.parser.ok) return -2;
    }

    int rc = emit_dict(core.interner, dictv, str_bytes, n_strings,
                       str_cap, L);
    return rc < 0 ? rc : core.e_used;
}

}  // extern "C"

// ------------------------------------------------ PyObject direct walk

#ifndef KTPU_NO_PYTHON

namespace {

// Python object -> Value tree, matching what parsing json.dumps(obj)
// produces: dict insertion order, bool-before-int dispatch, repr() float
// tokens (shortest round-trip, '.0' forced), str(int) integer tokens.
// Unsupported types and non-finite floats fail the conversion (the JSON
// path fails on Infinity/NaN tokens the same way) — the caller falls
// back to the serialize-then-parse route.
Value* py_to_value(PyObject* o, Arena* arena, bool* ok) {
    Value* v = arena->alloc();
    if (o == Py_None) { v->t = Value::Null; return v; }
    if (o == Py_True || o == Py_False) {
        v->t = Value::Bool;
        v->b = o == Py_True;
        return v;
    }
    if (PyLong_Check(o)) {
        v->t = Value::Num;
        int ovf = 0;
        long long ll = PyLong_AsLongLongAndOverflow(o, &ovf);
        if (ovf == 0 && !(ll == -1 && PyErr_Occurred())) {
            char buf[24];
            auto res = std::to_chars(buf, buf + sizeof buf, ll);
            v->str.assign(buf, res.ptr);
        } else {
            PyErr_Clear();
            PyObject* s = PyObject_Str(o);     // arbitrary precision
            if (s == nullptr) { PyErr_Clear(); *ok = false; return v; }
            Py_ssize_t n = 0;
            const char* u = PyUnicode_AsUTF8AndSize(s, &n);
            if (u == nullptr) { PyErr_Clear(); Py_DECREF(s); *ok = false; return v; }
            v->str.assign(u, size_t(n));
            Py_DECREF(s);
        }
        v->raw = v->str;
        return v;
    }
    if (PyFloat_Check(o)) {
        double d = PyFloat_AS_DOUBLE(o);
        if (!std::isfinite(d)) { *ok = false; return v; }
        v->t = Value::Num;
        char* s = PyOS_double_to_string(d, 'r', 0, Py_DTSF_ADD_DOT_0, nullptr);
        if (s == nullptr) { PyErr_Clear(); *ok = false; return v; }
        v->str = s;
        PyMem_Free(s);
        v->raw = v->str;
        return v;
    }
    if (PyUnicode_Check(o)) {
        v->t = Value::Str;
        Py_ssize_t n = 0;
        const char* u = PyUnicode_AsUTF8AndSize(o, &n);
        if (u == nullptr) { PyErr_Clear(); *ok = false; return v; }
        v->str.assign(u, size_t(n));
        return v;
    }
    if (PyDict_Check(o)) {
        v->t = Value::Obj;
        PyObject* key;
        PyObject* val;
        Py_ssize_t pos = 0;
        while (PyDict_Next(o, &pos, &key, &val)) {
            if (!PyUnicode_Check(key)) { *ok = false; return v; }
            Py_ssize_t n = 0;
            const char* u = PyUnicode_AsUTF8AndSize(key, &n);
            if (u == nullptr) { PyErr_Clear(); *ok = false; return v; }
            Value* child = py_to_value(val, arena, ok);
            if (!*ok) return v;
            v->obj.emplace_back(std::string(u, size_t(n)), child);
        }
        return v;
    }
    if (PyList_Check(o)) {
        v->t = Value::Arr;
        Py_ssize_t n = PyList_GET_SIZE(o);
        v->arr.reserve(size_t(n));
        for (Py_ssize_t i = 0; i < n; ++i) {
            Value* child = py_to_value(PyList_GET_ITEM(o, i), arena, ok);
            if (!*ok) return v;
            v->arr.push_back(child);
        }
        return v;
    }
    if (PyTuple_Check(o)) {                    // json.dumps serializes as array
        v->t = Value::Arr;
        Py_ssize_t n = PyTuple_GET_SIZE(o);
        v->arr.reserve(size_t(n));
        for (Py_ssize_t i = 0; i < n; ++i) {
            Value* child = py_to_value(PyTuple_GET_ITEM(o, i), arena, ok);
            if (!*ok) return v;
            v->arr.push_back(child);
        }
        return v;
    }
    *ok = false;
    return v;
}

}  // namespace

extern "C" {

// Packed flatten straight from live Python lists of dicts — no
// json.dumps, no JSON parse. Loaded via ctypes.PyDLL (the GIL stays
// held; the walk touches refcounted objects throughout). Same output
// and -1/-4 retry protocol as ktpu_flatten_packed; -5 = an object the
// JSON model can't express (caller falls back to the dumps path).
int ktpu_flatten_packed_py(
    void* handle, PyObject* docs, PyObject* reqs,
    int n_docs, int max_slots, int e_cap, int32_t* e_needed,
    uint32_t* cells, uint32_t* bmeta, uint32_t* dictv,
    uint8_t* str_bytes,
    int32_t* n_strings, int str_cap) {

    Ctx* ctx = static_cast<Ctx*>(handle);
    if (!PyList_Check(docs) || PyList_GET_SIZE(docs) != n_docs) return -3;
    if (reqs != nullptr && reqs != Py_None &&
        (!PyList_Check(reqs) || PyList_GET_SIZE(reqs) != n_docs)) return -3;
    const bool have_reqs = reqs != nullptr && reqs != Py_None;

    Arena arena;
    PackedCore core(ctx, e_cap, max_slots, cells, bmeta);
    for (int b = 0; b < n_docs; ++b) {
        arena.reset();
        bool ok = true;
        const Value* root = py_to_value(PyList_GET_ITEM(docs, b), &arena, &ok);
        if (!ok) return -5;
        const Value* env = nullptr;
        if (have_reqs) {
            env = py_to_value(PyList_GET_ITEM(reqs, b), &arena, &ok);
            if (!ok) return -5;
        }
        int rc = core.doc(root, env, b, e_needed);
        if (rc != 0) return rc;
    }
    int rc = emit_dict(core.interner, dictv, str_bytes, n_strings,
                       str_cap, ctx->str_len_cap);
    return rc < 0 ? rc : core.e_used;
}

}  // extern "C"

#endif  // KTPU_NO_PYTHON
